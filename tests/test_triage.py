"""``gator triage`` (ISSUE 18): the one-command incident picture.

Three layers: (1) ``build_report``/``render`` are pure over a bundle
dict, so the cross-linking logic is pinned on a synthetic incident;
(2) offline mode reconstructs degradations-in-force from the
``overload.degraded`` stamps in a ROTATED flight-recorder sink set and
inventories the snapshot-spill root; (3) the live e2e chain — a real
WebhookServer + SLOEngine (injected clock) + DegradationRegistry:
a chaos-slowed admission breaches ``admission-latency-p99`` at page
tier, the map activates ``ns_cache_stale``, a shed lands, and
``collect_live`` + ``build_report`` walk objective -> degradation ->
top template -> slowest trace -> shed in one chain entry."""

import json
import time

import pytest

from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.gator import triage_cmd
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import costattr, flightrec, slo, tracing
from gatekeeper_tpu.resilience import overload as ovl
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.unstructured import load_yaml_file
from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer

LIB = "/root/repo/library/general"


# --- (1) build_report / render: pure over a synthetic bundle ---------------

def _synthetic_bundle():
    return {
        "mode": "live", "url": "http://test",
        "slo": {
            "generated_at": 100.0, "pressure": 0.8,
            "objectives": [
                {"name": "admission-latency-p99", "type": "latency",
                 "cluster": "", "target": 0.99, "sli": 0.5,
                 "compliant": False,
                 "burn": {"300s": 50.0, "3600s": 50.0},
                 "breach": True, "breach_tier": "page",
                 "degradation": ["ns_cache_stale", "extdata_stale",
                                 "shed_harder"],
                 "degradation_active": ["ns_cache_stale"]},
                {"name": "audit-snapshot-staleness", "type": "staleness",
                 "cluster": "", "sli": 12.0, "compliant": True,
                 "burn": {}, "breach": False, "breach_tier": "",
                 "degradation": ["audit_yield_release", "resync_defer"],
                 "degradation_active": []},
            ],
        },
        "cost": {"top": [
            {"template": "K8sRequiredLabels", "seconds": 4.2,
             "passes": 90},
            {"template": "K8sContainerLimits", "seconds": 0.3,
             "passes": 9},
        ], "tenants": [{"tenant": "team-a", "seconds": 4.0}]},
        "overload": {"mode": "serving", "brownout": 1, "degraded": [
            {"action": "ns_cache_stale", "cluster": "",
             "objectives": ["admission-latency-p99"]}]},
        "traces": {"kept": 2, "traces": [
            {"trace_id": "aaaa", "root": "webhook.request",
             "duration_s": 0.05, "n_spans": 3},
            {"trace_id": "bbbb", "root": "webhook.request",
             "duration_s": 0.44, "n_spans": 5},
        ]},
        "decisions": {"recorded": 3, "decisions": [
            {"ts": 103.0, "decision": "shed", "uid": "shed-9",
             "reason": "chaos", "tenant": "team-a",
             "overload": {"degraded": ["ns_cache_stale"]}},
            {"ts": 102.0, "decision": "allow", "uid": "ok-1",
             "trace_id": "aaaa"},
            {"ts": 101.0, "decision": "deny", "uid": "slow-0",
             "trace_id": "bbbb", "cost": 0.4},
        ]},
    }


def test_build_report_cross_links_the_chain():
    bundle = _synthetic_bundle()
    report = triage_cmd.build_report(bundle)

    assert report["objectives_total"] == 2
    assert [ev["name"] for ev in report["breaching"]] == \
        ["admission-latency-p99"]
    # authoritative overload view wins over the per-objective fallback
    assert report["degraded"][0]["action"] == "ns_cache_stale"
    assert report["top_templates"][0]["template"] == "K8sRequiredLabels"
    # slowest-first, and the exemplar links the slowest trace that has
    # a decision — bbbb (0.44s) -> the deny of slow-0
    assert report["slowest_traces"][0]["trace_id"] == "bbbb"
    assert report["exemplar"]["trace"]["trace_id"] == "bbbb"
    assert report["exemplar"]["decisions"][0]["uid"] == "slow-0"
    assert report["decision_counts"] == {"shed": 1, "allow": 1,
                                         "deny": 1}
    assert [e["uid"] for e in report["recent_sheds"]] == ["shed-9"]

    (chain,) = report["chains"]
    assert chain["objective"] == "admission-latency-p99"
    assert chain["tier"] == "page"
    assert chain["degradations"] == ["ns_cache_stale"]
    # one active of three mapped: next escalation step is named
    assert chain["next_degradation"] == "extdata_stale"
    assert chain["top_template"] == "K8sRequiredLabels"
    assert chain["slowest_trace"] == "bbbb"
    assert chain["recent_sheds"] == 1


def test_render_names_every_chain_segment():
    bundle = _synthetic_bundle()
    text = triage_cmd.render(bundle, triage_cmd.build_report(bundle))
    assert "SLO: 1/2 objectives breaching" in text
    assert "admission-latency-p99" in text
    assert "degradations active: ns_cache_stale" in text
    assert "next if sustained: extdata_stale" in text
    assert "Degradations in force:" in text
    assert "K8sRequiredLabels" in text
    assert "Slowest exemplar trace: bbbb" in text
    assert "uid=shed-9" in text and "reason=chaos" in text
    assert "Chain:" in text
    chain_line = [ln for ln in text.splitlines()
                  if "admission-latency-p99 breaching" in ln][0]
    for seg in ("activated ns_cache_stale",
                "top template K8sRequiredLabels",
                "slowest trace bbbb", "1 recent sheds"):
        assert seg in chain_line, chain_line


def test_render_flags_unavailable_endpoints_and_healthy_chain():
    bundle = {"mode": "live", "url": "http://test",
              "slo": {"objectives": []},
              "cost": {"error": "/debug/cost: boom"},
              "overload": {}, "traces": {}, "decisions": {}}
    text = triage_cmd.render(bundle, triage_cmd.build_report(bundle))
    assert "!! cost: unavailable" in text
    assert "nothing to triage" in text


# --- (2) offline mode: rotated sink + degraded stamps + spill --------------

def test_triage_offline_reconstructs_from_rotated_sink(tmp_path):
    sink = tmp_path / "decisions.jsonl"
    wall = {"t": 1000.0}
    rec = flightrec.FlightRecorder(
        sink_path=str(sink), wall=lambda: wall["t"],
        sink_max_bytes=300, sink_keep=8)
    reg = ovl.DegradationRegistry()
    ovl.install_degradations(reg)
    try:
        for i in range(6):  # healthy stretch
            wall["t"] += 1
            rec.record("validate", "allow", uid=f"ok-{i}",
                       tenant="team-a")
        reg.activate("ns_cache_stale", "admission-latency-p99")
        for i in range(4):  # degraded stretch: stamps ride each line
            wall["t"] += 1
            rec.record("validate", "shed" if i == 3 else "allow",
                       uid=f"deg-{i}", reason="chaos" if i == 3 else "")
    finally:
        ovl.uninstall_degradations()
        rec.close()
    assert rec.rotations > 0  # the 300-byte cap really rotated

    spill = tmp_path / "spill"
    (spill / "cluster-a").mkdir(parents=True)
    (spill / "cluster-a" / "snap.npz").write_bytes(b"x" * 8)

    bundle = triage_cmd.collect_offline(str(sink), spill=str(spill))
    # the rotated set reads as one stream, newest first
    assert bundle["decisions"]["recorded"] == 10
    assert bundle["decisions"]["rotated_files"] > 1
    assert bundle["decisions"]["decisions"][0]["uid"] == "deg-3"
    # degradations-in-force reconstructed from the decision stamps
    assert bundle["overload"]["reconstructed"] is True
    assert [d["action"] for d in bundle["overload"]["degraded"]] == \
        ["ns_cache_stale"]
    assert bundle["spill"]["clusters"][0]["cluster"] == "cluster-a"
    assert bundle["spill"]["clusters"][0]["files"] == 1

    report = triage_cmd.build_report(bundle)
    assert report["degraded"][0]["action"] == "ns_cache_stale"
    assert [e["uid"] for e in report["recent_sheds"]] == ["deg-3"]
    text = triage_cmd.render(bundle, report)
    assert "ns_cache_stale" in text
    assert "Audit snapshot spills" in text and "cluster-a" in text


def test_triage_cli_arg_validation_and_json(tmp_path, capsys):
    # exactly one of --url / -f
    assert triage_cmd.run_cli([]) == 2
    assert triage_cmd.run_cli(["--url", "http://x", "-f", "y"]) == 2
    capsys.readouterr()

    sink = tmp_path / "d.jsonl"
    rec = flightrec.FlightRecorder(sink_path=str(sink))
    rec.record("validate", "allow", uid="u0")
    rec.close()
    # nothing breaching offline -> exit 0, and --json round-trips
    assert triage_cmd.run_cli(["-f", str(sink), "-o", "json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["bundle"]["mode"] == "offline"
    assert out["report"]["chains"] == []
    assert out["bundle"]["decisions"]["decisions"][0]["uid"] == "u0"


# --- (3) live e2e: breach -> degradation -> triage chain -------------------

def test_triage_live_chain_end_to_end():
    """The ISSUE 18 acceptance chain, against the real HTTP surface:
    slow admission -> admission-latency-p99 breaches page tier on the
    injected SLO clock -> the degradation map activates ns_cache_stale
    -> a later admission sheds (stamped degraded) -> one collect_live
    bundle cross-links all of it."""
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=[WEBHOOK_EP])
    client.add_template(load_yaml_file(
        f"{LIB}/requiredlabels/template.yaml")[0])
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "everything-labeled"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}},
    })

    m = MetricsRegistry()
    attr = costattr.CostAttribution(metrics=m)
    rec = flightrec.FlightRecorder(metrics=m)
    ctl = ovl.OverloadController(ovl.OverloadConfig(), metrics=m)
    tracer = tracing.Tracer(seed=0, ring_capacity=256)
    reg = ovl.DegradationRegistry(metrics=m)
    clk = {"t": 0.0}
    eng = slo.SLOEngine(
        m, objectives=[slo.DEFAULT_OBJECTIVES[0]],  # admission-latency
        degradations=reg, clock=lambda: clk["t"])
    batcher = Batcher(client, small_batch=0, metrics=m).start()
    handler = ValidationHandler(client, batcher=batcher, metrics=m,
                                overload=ctl, failure_policy="fail")
    srv = WebhookServer(validation_handler=handler, metrics=m, port=0,
                        cost_attribution=attr, slo_engine=eng,
                        flight_recorder=rec).start()

    import urllib.request

    def post(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/admit",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    def body(uid):
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": uid, "operation": "CREATE",
                            "kind": {"group": "", "version": "v1",
                                     "kind": "Namespace"},
                            "name": uid, "namespace": "",
                            "userInfo": {"username": "it"},
                            "object": {"apiVersion": "v1",
                                       "kind": "Namespace",
                                       "metadata": {"name": uid}}}}

    plan = FaultPlan([
        {"site": "webhook.review", "mode": "sleep", "delay_s": 0.4,
         "times": 1},
        {"site": "webhook.overload", "mode": "error", "after": 6,
         "times": 1},
    ])
    try:
        with tracing.activate(tracer), costattr.activate(attr), \
                flightrec.activate(rec), ovl.activate(ctl), \
                ovl.activate_degradations(reg), inject(plan):
            eng.tick()  # t=0 baseline sample: nothing served yet
            out = post(body("slow-0"))  # chaos: 0.4s > 250ms threshold
            assert out["response"]["allowed"] is False  # missing label
            # one slow of one total against a 1% budget: burn 100 >=
            # 14.4 on both page windows once the window has aged
            clk["t"] = 300.0
            eng.tick()
            assert reg.is_active("ns_cache_stale")
            for i in range(1, 6):
                post(body(f"ns-{i}"))
            shed = post(body("shed-6"))  # gate call 7: chaos shed
            assert shed["response"]["status"]["code"] == 429

            bundle = triage_cmd.collect_live(
                f"http://127.0.0.1:{srv.port}")
            bundle["collected_at"] = time.time()
            report = triage_cmd.build_report(bundle)
    finally:
        srv.stop()
        batcher.stop()

    for key in triage_cmd.ENDPOINTS:
        assert "error" not in bundle[key], bundle[key]

    (chain,) = report["chains"]
    assert chain["objective"] == "admission-latency-p99"
    assert chain["tier"] == "page"
    assert chain["burn"]["300s"] >= 14.4
    assert chain["degradations"] == ["ns_cache_stale"]
    assert chain["next_degradation"] == "extdata_stale"
    assert chain["top_template"] == "K8sRequiredLabels"
    assert chain["recent_sheds"] == 1

    # the authoritative /debug/overload view carries the holder
    assert report["degraded"][0]["action"] == "ns_cache_stale"
    assert report["degraded"][0]["objectives"] == \
        ["admission-latency-p99"]
    # slowest trace is the chaos-slowed request, linked to its decision
    ex = report["exemplar"]
    assert ex["trace"]["duration_s"] >= 0.4
    assert chain["slowest_trace"] == ex["trace"]["trace_id"]
    assert any(d["uid"] == "slow-0" for d in ex["decisions"])
    # the shed happened AFTER activation: its record is stamped
    shed_rec = next(e for e in report["recent_sheds"]
                    if e["uid"] == "shed-6")
    assert shed_rec["overload"]["degraded"] == ["ns_cache_stale"]

    text = triage_cmd.render(bundle, report)
    assert "admission-latency-p99" in text
    assert "ns_cache_stale" in text
    assert "K8sRequiredLabels" in text
    assert "uid=shed-6" in text
    assert "Chain:" in text


def test_collect_live_survives_a_dead_endpoint():
    calls = []

    def fetch(url, timeout):
        calls.append(url)
        if "/debug/cost" in url:
            raise OSError("connection refused")
        return {"ok": True}

    bundle = triage_cmd.collect_live("http://h:1", cluster="a",
                                     fetch=fetch)
    assert bundle["cost"]["error"].startswith("/debug/cost")
    assert bundle["slo"] == {"ok": True}
    # cluster scopes the slo + decisions views
    assert any(u.endswith("/debug/slo?cluster=a") for u in calls)
    assert any(u.endswith("/debug/decisions?cluster=a") for u in calls)
