"""Exposition-format coverage for the bucketed-histogram registry:
reservoir-bias fix (quantiles and sum/count describe the same lifetime
population), exemplars linking buckets to trace ids, OpenMetrics
content-type negotiation on /metrics, and the label-cardinality guard."""

import json
import urllib.request

from gatekeeper_tpu.metrics import registry as M
from gatekeeper_tpu.metrics.registry import (COUNT_BUCKETS,
                                             DURATION_BUCKETS,
                                             MetricsRegistry, PREFIX)
from gatekeeper_tpu.observability import tracing
from gatekeeper_tpu.webhook.server import WebhookServer


# --- bucketed histograms ---------------------------------------------------

def test_lifetime_buckets_replace_the_reservoir_window():
    """The reservoir bias: the old summary computed quantiles over a
    deque(maxlen=4096) window while sum/count were lifetime — a late
    burst dominated the quantiles while count said otherwise.  Buckets
    are lifetime like the sums: 9000 fast observations outweigh a late
    100-observation slow burst at P50 AND the +Inf cumulative equals
    count."""
    reg = MetricsRegistry()
    for _ in range(9000):
        reg.observe("lat_seconds", 0.001)
    for _ in range(100):
        reg.observe("lat_seconds", 9.0)
    h = reg.get_histogram("lat_seconds")
    assert h["count"] == 9100
    assert sum(h["buckets"]) == 9100  # buckets ARE the population
    lines = reg.render().splitlines()
    inf_line = next(ln for ln in lines
                    if ln.startswith(f'{PREFIX}lat_seconds_bucket')
                    and 'le="+Inf"' in ln)
    assert inf_line.endswith(" 9100")
    # the compat quantile shim reads the lifetime distribution: P50 is
    # in the fast decade, not the late slow burst's
    p50 = next(float(ln.rsplit(" ", 1)[1]) for ln in lines
               if ln.startswith(f'{PREFIX}lat_seconds{{quantile="0.5"}}'))
    assert p50 <= 0.005
    # and P99.. the slow tail is still visible at the right rank: 100 of
    # 9100 is ~1.1%, so P99 lands at the fast/slow boundary or above
    p99 = next(float(ln.rsplit(" ", 1)[1]) for ln in lines
               if ln.startswith(f'{PREFIX}lat_seconds{{quantile="0.99"}}'))
    assert p99 >= 0.001


def test_bucket_bounds_by_name_and_override():
    reg = MetricsRegistry()
    reg.observe("x_seconds", 0.01)
    reg.observe("batch_size", 7)
    assert reg.get_histogram("x_seconds")["bounds"] == DURATION_BUCKETS
    assert reg.get_histogram("batch_size")["bounds"] == COUNT_BUCKETS
    reg.set_buckets("depth", (5.0, 50.0))
    reg.observe("depth", 7)
    h = reg.get_histogram("depth")
    assert h["bounds"] == (5.0, 50.0)
    assert h["buckets"] == [0, 1, 0]  # (<=5, <=50, +Inf)


def test_cumulative_le_series_shape():
    reg = MetricsRegistry()
    for v in (0.0004, 0.002, 0.002, 7.0, 40.0):
        reg.observe("d_seconds", v, {"p": "x"})
    lines = [ln for ln in reg.render().splitlines()
             if ln.startswith(f'{PREFIX}d_seconds_bucket')]
    # le rides LAST after the user labels; counts are cumulative
    assert lines[0].startswith(f'{PREFIX}d_seconds_bucket'
                               f'{{p="x",le="0.0005"}} 1')
    by_le = {ln.split('le="')[1].split('"')[0]: int(ln.rsplit(" ", 1)[1])
             for ln in lines}
    assert by_le["0.0025"] == 3
    assert by_le["10"] == 4
    assert by_le["30"] == 4
    assert by_le["+Inf"] == 5


# --- exemplars -------------------------------------------------------------

def test_exemplars_carry_the_ambient_trace_id():
    reg = MetricsRegistry()
    tracer = tracing.Tracer(seed=7)
    with tracing.activate(tracer):
        with tracing.span("req") as sp:
            reg.observe("lat_seconds", 0.03)
            tid = sp.trace_id
    h = reg.get_histogram("lat_seconds")
    exemplars = [e for e in h["exemplars"] if e is not None]
    assert len(exemplars) == 1
    assert exemplars[0][0] == tid
    assert exemplars[0][1] == 0.03
    # exemplars render ONLY in the OpenMetrics flavor
    om = reg.render(openmetrics=True)
    assert f'# {{trace_id="{tid}"}} 0.03' in om
    assert om.rstrip().endswith("# EOF")
    legacy = reg.render()
    assert "trace_id" not in legacy
    assert "# EOF" not in legacy


def test_no_tracer_no_exemplar():
    reg = MetricsRegistry()
    reg.observe("lat_seconds", 0.03)
    h = reg.get_histogram("lat_seconds")
    assert all(e is None for e in h["exemplars"])


# --- label-cardinality guard ----------------------------------------------

def test_label_overflow_folds_into_other():
    reg = MetricsRegistry(max_label_sets=3)
    for i in range(6):
        reg.inc_counter("per_template_count", {"template": f"T{i}"})
    # first 3 labelsets stored verbatim, the rest folded
    for i in range(3):
        assert reg.get_counter("per_template_count",
                               {"template": f"T{i}"}) == 1
    assert reg.get_counter("per_template_count",
                           {"template": "other"}) == 3
    assert reg.get_counter(M.DROPPED_LABELS) == 3
    # totals survive the fold
    assert reg.counter_total("per_template_count") == 6


def test_cardinality_guard_is_per_metric_name_and_keeps_repeats():
    reg = MetricsRegistry(max_label_sets=2)
    reg.inc_counter("a_count", {"k": "x"})
    reg.inc_counter("a_count", {"k": "y"})
    reg.inc_counter("a_count", {"k": "x"})  # existing set: no fold
    reg.inc_counter("b_count", {"k": "z"})  # different metric: own budget
    assert reg.get_counter("a_count", {"k": "x"}) == 2
    assert reg.get_counter("b_count", {"k": "z"}) == 1
    assert reg.get_counter(M.DROPPED_LABELS) == 0
    reg.observe("h_seconds", 1.0, {"k": "p"})
    reg.observe("h_seconds", 1.0, {"k": "q"})
    reg.observe("h_seconds", 1.0, {"k": "r"})  # folds
    assert reg.get_histogram("h_seconds", {"k": "other"})["count"] == 1
    assert reg.get_counter(M.DROPPED_LABELS) == 1


# --- /metrics content negotiation -----------------------------------------

def test_metrics_endpoint_negotiates_openmetrics():
    reg = MetricsRegistry()
    reg.inc_counter("requests_count")
    tracer = tracing.Tracer(seed=0)
    with tracing.activate(tracer):
        with tracing.span("x"):
            reg.observe("lat_seconds", 0.02)
    srv = WebhookServer(metrics=reg, port=0).start()
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(urllib.request.Request(url)) as r:
            assert r.headers["Content-Type"] == \
                "text/plain; version=0.0.4"
            body = r.read().decode()
        assert "# EOF" not in body
        assert f"# TYPE {PREFIX}lat_seconds histogram" in body
        req = urllib.request.Request(url, headers={
            "Accept": "application/openmetrics-text; version=1.0.0"})
        with urllib.request.urlopen(req) as r:
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            om = r.read().decode()
        assert om.rstrip().endswith("# EOF")
        assert "trace_id=" in om  # the exemplar made it to the wire
    finally:
        srv.stop()


def test_openmetrics_escapes_label_values_in_exemplars():
    reg = MetricsRegistry()
    reg.inc_counter("errs_count", {"msg": 'say "hi"\nback\\slash'})
    om = reg.render(openmetrics=True)
    line = next(ln for ln in om.splitlines()
                if ln.startswith(f"{PREFIX}errs_count"))
    assert '\\"hi\\"' in line and "\nback" not in line


def test_render_parses_as_name_labels_value():
    """Every sample line keeps the NAME{LABELS} VALUE shape (exemplar
    suffixes only in OpenMetrics, after ' # ')."""
    reg = MetricsRegistry()
    reg.inc_counter("c_count", {"a": "b"})
    reg.set_gauge("g", 2)
    reg.observe("h_seconds", 0.1, {"x": "y"})
    for ln in reg.render().splitlines():
        if ln.startswith("#"):
            continue
        name_part, value = ln.rsplit(" ", 1)
        assert value != ""
        float(value)  # parses


# --- reservoir-sampled exemplars (PR 12) -----------------------------------

def test_exemplar_reservoir_survives_a_burst():
    """A burst of boring observations into one bucket no longer evicts
    the interesting (slow) trace: retention is a uniform reservoir and
    the RENDERED exemplar is the reservoir's max-value entry."""
    reg = MetricsRegistry()
    tracer = tracing.Tracer(seed=7)
    with tracing.activate(tracer):
        with tracing.span("interesting") as sp:
            reg.observe("lat_seconds", 0.024)  # lands in the 0.025 bucket
            slow_tid = sp.trace_id
        # 500-observation burst into the SAME bucket, all faster: under
        # last-write-wins the final one would own the exemplar slot
        for i in range(500):
            with tracing.span(f"boring-{i}"):
                reg.observe("lat_seconds", 0.011)
    h = reg.get_histogram("lat_seconds")
    i = [j for j, e in enumerate(h["exemplars"]) if e is not None]
    assert len(i) == 1
    bucket = i[0]
    # rendered exemplar = the bucket's max-value observation = the
    # slow trace (pinned; the burst cannot displace it)
    assert h["exemplars"][bucket][0] == slow_tid
    assert h["exemplars"][bucket][1] == 0.024
    res = h["exemplar_reservoir"][bucket]
    assert 1 <= len(res) <= 4
    # the reservoir is NOT just the last K observations (anti-recency):
    # with the seeded RNG at least one retained entry predates the
    # burst's tail window
    om = reg.render(openmetrics=True)
    assert f'trace_id="{slow_tid}"' in om


def test_exemplar_reservoir_uniform_not_recency():
    """Deterministic (seeded) check that retention spans the sequence
    instead of the tail: observe 200 traced values into one bucket and
    assert some retained exemplar comes from the first half."""
    reg = MetricsRegistry()
    tracer = tracing.Tracer(seed=3)
    tids = []
    with tracing.activate(tracer):
        for i in range(200):
            with tracing.span(f"s{i}") as sp:
                reg.observe("lat_seconds", 0.011)
                tids.append(sp.trace_id)
    h = reg.get_histogram("lat_seconds")
    bucket = [j for j, e in enumerate(h["exemplars"])
              if e is not None][0]
    res = h["exemplar_reservoir"][bucket]
    assert len(res) == 4
    order = {tid: i for i, tid in enumerate(tids)}
    retained = sorted(order[e[0]] for e in res)
    # last-write-wins / pure recency would retain only 196..199
    assert retained[0] < 100, retained


def test_exemplar_reservoir_single_observation_compat():
    """One traced observation: exemplars behave exactly as before
    (reservoir of one, rendered as-is)."""
    reg = MetricsRegistry()
    tracer = tracing.Tracer(seed=9)
    with tracing.activate(tracer):
        with tracing.span("only") as sp:
            reg.observe("lat_seconds", 0.2)
            tid = sp.trace_id
    h = reg.get_histogram("lat_seconds")
    ex = [e for e in h["exemplars"] if e is not None]
    assert ex == [(tid, 0.2, ex[0][2])]
