"""Observability integration: (1) THE differential — attribution + SLO
engine + flight recorder + tracer fully ON vs fully OFF is verdict- and
patch-bit-identical over the library corpus (observability must never
perturb enforcement); (2) the end-to-end identifiability chain — a
deliberately slow, high-occupancy template walks from the P99 histogram
bucket's exemplar trace id to its /debug/traces span, tops /debug/cost,
and the burst's shed decision is explained in /debug/decisions."""

import json
import urllib.error
import urllib.request

import pytest

from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.drivers.cel_driver import CELDriver
from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
from gatekeeper_tpu.metrics.registry import MetricsRegistry
from gatekeeper_tpu.observability import costattr, flightrec, slo, tracing
from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
from gatekeeper_tpu.resilience import overload as ovl
from gatekeeper_tpu.resilience.faults import FaultPlan, inject
from gatekeeper_tpu.target.target import K8sValidationTarget
from gatekeeper_tpu.utils.synthetic import load_library, make_cluster_objects
from gatekeeper_tpu.utils.unstructured import gvk_of, load_yaml_file
from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
from gatekeeper_tpu.webhook.server import WebhookServer

LIB = "/root/repo/library/general"


# --- (1) the on-vs-off differential ---------------------------------------

@pytest.fixture(scope="module")
def library_setup():
    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client)
    objects = make_cluster_objects(90, seed=29)
    return client, tpu, objects


def _sweep_signature(run):
    return (
        dict(run.total_violations),
        {k: [(v.message, v.kind, v.name, v.namespace,
              v.enforcement_action) for v in vs]
         for k, vs in run.kept.items()},
    )


def _admission_bodies(objects):
    bodies = []
    for i, obj in enumerate(objects):
        g, v, k = gvk_of(obj)
        bodies.append({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": f"u{i}", "operation": "CREATE",
                "kind": {"group": g, "version": v, "kind": k},
                "name": (obj.get("metadata") or {}).get("name", ""),
                "namespace": (obj.get("metadata") or {}).get(
                    "namespace", ""),
                "userInfo": {"username": "differential"},
                "object": obj,
            },
        })
    return bodies


def _resp_signature(resp):
    return (resp.allowed, resp.message, resp.code, tuple(resp.warnings),
            resp.uid)


def test_observability_on_vs_off_bit_identical(library_setup, tmp_path):
    """Sweep verdicts, admission responses and mutation patches with the
    whole observability stack installed equal the bare run bit-for-bit."""
    client, tpu, objects = library_setup
    bodies = _admission_bodies(objects[:40])

    def sweep(metrics=None):
        mgr = AuditManager(
            client, lister=lambda: iter(objects),
            config=AuditConfig(chunk_size=32, exact_totals=False,
                               pipeline="off"),
            evaluator=ShardedEvaluator(tpu, make_mesh(),
                                       violations_limit=20),
            metrics=metrics,
        )
        return _sweep_signature(mgr.audit())

    def admissions(handler):
        return [_resp_signature(handler.handle(b)) for b in bodies]

    # OFF: no tracer, no attribution, no recorder, no metrics
    base_sweep = sweep()
    base_adm = admissions(ValidationHandler(client))
    assert any(not s[0] for s in base_adm)  # non-vacuous: real denies
    assert sum(base_sweep[0].values()) > 0

    # ON: everything installed — tracer (keep-all), attribution, flight
    # recorder with a JSONL sink, metrics, SLO engine ticking mid-run
    # with the degradation maps ARMED (registry installed, all
    # objectives healthy — the --slo-degradation on steady state)
    m = MetricsRegistry()
    attr = costattr.CostAttribution(metrics=m)
    rec = flightrec.FlightRecorder(
        metrics=m, sink_path=str(tmp_path / "d.jsonl"))
    reg = ovl.DegradationRegistry(metrics=m)
    eng = slo.SLOEngine(m, degradations=reg)
    tracer = tracing.Tracer(seed=0, ring_capacity=512)
    with tracing.activate(tracer), costattr.activate(attr), \
            flightrec.activate(rec), ovl.activate_degradations(reg):
        eng.tick()
        on_sweep = sweep(metrics=m)
        eng.tick()
        on_adm = admissions(ValidationHandler(client, metrics=m))
        eng.tick()

    assert on_sweep == base_sweep
    assert on_adm == base_adm
    # and the observability actually observed: spans kept, costs
    # attributed, every admission decision recorded, SLOs evaluated,
    # and the armed-but-healthy maps never fired
    assert tracer.kept > 0
    assert attr.total_seconds() > 0
    assert rec.recorded == len(bodies)
    assert eng.snapshot()["objectives"]
    assert reg.active() == [] and not eng.degradation_trajectory


def test_mutation_on_vs_off_bit_identical():
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane import MutationLane

    system = MutationSystem()
    system.upsert_unstructured({
        "apiVersion": "mutations.gatekeeper.sh/v1", "kind": "Assign",
        "metadata": {"name": "set-policy"},
        "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                              "kinds": ["Pod"]}],
                 "location": "spec.priorityClassName",
                 "parameters": {"assign": {"value": "low"}}},
    })
    objects = [o for o in make_cluster_objects(60, seed=5)
               if o.get("kind") == "Pod"]
    assert objects
    lane = MutationLane(system)

    def signature():
        return [(o.changed, o.patch, o.error, o.lane)
                for o in lane.mutate_objects(objects)]

    base = signature()
    m = MetricsRegistry()
    attr = costattr.CostAttribution(metrics=m)
    tracer = tracing.Tracer(seed=1)
    with tracing.activate(tracer), costattr.activate(attr):
        on = signature()
    assert on == base
    assert any(p for _c, p, _e, _l in base)  # real patches emitted
    assert attr.total_seconds(costattr.EP_MUTATION) > 0


# --- (2) the end-to-end identifiability chain ------------------------------

def test_slow_template_identifiable_end_to_end(tmp_path):
    """A deliberately slow admission against a high-occupancy template:
    P99 histogram bucket -> exemplar trace id -> /debug/traces span ->
    /debug/cost top entry -> the burst's shed decision visible in
    /debug/decisions.  One flow through the live HTTP surface."""
    client = Client(target=K8sValidationTarget(), drivers=[TpuDriver()],
                    enforcement_points=[WEBHOOK_EP])
    # the HOT template: K8sRequiredLabels matching every kind (no kinds
    # matcher) — it occupies every mask cell of every request, so it
    # must top /debug/cost.  The cold one only ever matches Pods.
    client.add_template(load_yaml_file(
        f"{LIB}/requiredlabels/template.yaml")[0])
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "everything-labeled"},
        "spec": {"parameters": {"labels": [{"key": "owner"}]}},
    })
    client.add_template(load_yaml_file(
        f"{LIB}/containerlimits/template.yaml")[0])
    client.add_constraint({
        "apiVersion": "constraints.gatekeeper.sh/v1beta1",
        "kind": "K8sContainerLimits",
        "metadata": {"name": "pod-limits"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Pod"]}]},
                 "parameters": {"cpu": "200m", "memory": "1Gi"}},
    })

    m = MetricsRegistry()
    attr = costattr.CostAttribution(metrics=m)
    rec = flightrec.FlightRecorder(metrics=m)
    ctl = ovl.OverloadController(ovl.OverloadConfig(), metrics=m)
    tracer = tracing.Tracer(seed=0, ring_capacity=256)
    # small_batch=0: every admission takes the device grid, so webhook
    # attribution flows through device.query_batch
    batcher = Batcher(client, small_batch=0, metrics=m).start()
    handler = ValidationHandler(client, batcher=batcher, metrics=m,
                                overload=ctl, failure_policy="fail")
    srv = WebhookServer(validation_handler=handler, metrics=m, port=0,
                        cost_attribution=attr, slo_engine=None,
                        flight_recorder=rec).start()

    def post(body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/admit",
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    def get(path, accept=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}",
            headers={"Accept": accept} if accept else {})
        with urllib.request.urlopen(req) as r:
            return r.read().decode()

    def body(uid, kind="Namespace"):
        obj = {"apiVersion": "v1", "kind": kind,
               "metadata": {"name": uid}}
        if kind == "Pod":
            obj["spec"] = {"containers": [{"name": "c", "image": "i"}]}
        return {"apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {"uid": uid, "operation": "CREATE",
                            "kind": {"group": "", "version": "v1",
                                     "kind": kind},
                            "name": uid, "namespace": "",
                            "userInfo": {"username": "it"},
                            "object": obj}}

    # chaos: the FIRST review is slow (the P99 outlier); the 10th
    # admission gate call sheds (the overload story to explain later)
    plan = FaultPlan([
        {"site": "webhook.review", "mode": "sleep", "delay_s": 0.4,
         "times": 1},
        {"site": "webhook.overload", "mode": "error", "after": 9,
         "times": 1},
    ])
    try:
        with tracing.activate(tracer), costattr.activate(attr), \
                flightrec.activate(rec), ovl.activate(ctl), inject(plan):
            out = post(body("slow-0"))
            assert out["response"]["allowed"] is False  # missing label
            for i in range(1, 8):
                post(body(f"ns-{i}"))
            post(body("pod-8", kind="Pod"))
            shed_out = post(body("shed-9"))
            assert shed_out["response"]["status"]["code"] == 429

            # 1) the P99 bucket carries an exemplar: OpenMetrics render
            om = get("/metrics",
                     accept="application/openmetrics-text; version=1.0.0")
            slow_lines = [
                ln for ln in om.splitlines()
                if ln.startswith("gatekeeper_validation_request_"
                                 "duration_seconds_bucket")
                and "trace_id=" in ln
                and float(ln.split('le="')[1].split('"')[0]
                          .replace("+Inf", "inf")) >= 0.4]
            assert slow_lines, om
            slow_tid = slow_lines[0].split('trace_id="')[1].split('"')[0]

            # 2) that trace id resolves in /debug/traces, and its
            # timeline shows WHERE the time went (webhook.review slow)
            traces = json.loads(get("/debug/traces"))["traces"]
            tr = next(t for t in traces if t["trace_id"] == slow_tid)
            assert tr["duration_s"] >= 0.4
            review = next(s for s in tr["spans"]
                          if s["name"] == "webhook.review")
            assert review["duration_s"] >= 0.4
            assert next(s for s in tr["spans"]
                        if s["name"] == "webhook.request")[
                "attributes"]["uid"] == "slow-0"

            # 3) /debug/cost: the high-occupancy template tops the table
            cost = json.loads(get("/debug/cost"))
            assert cost["top"][0]["template"] == "K8sRequiredLabels"
            templates = {t["template"] for t in cost["top"]}
            assert "K8sContainerLimits" in templates

            # 4) the shed decision is explained in /debug/decisions
            dec = json.loads(get("/debug/decisions?uid=shed-9"))
            e = dec["decisions"][0]
            assert e["decision"] == "shed"
            assert e["reason"] == "chaos"
            assert e["overload"]["inflight_limit"] >= 1
            assert e["trace_id"]  # links back into the timeline
            # and the slow request's decision is there too
            slow_dec = json.loads(
                get("/debug/decisions?uid=slow-0"))["decisions"][0]
            assert slow_dec["decision"] == "deny"
            assert slow_dec["trace_id"] == slow_tid
    finally:
        srv.stop()
        batcher.stop()
