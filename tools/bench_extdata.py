"""External-data join-lane bench: deduped bulk calls vs per-key RTTs.

The registry-lookup workload the ROADMAP names (image-digest-style
verification): a synthetic pod corpus whose container images draw from a
bounded registry namespace, one validation-side external-data template
(errors lane) and one mutation-side Assign placeholder, evaluated in
audit chunks.  Measured per chunk size:

- ``perkey_round_trips``  — transport sends the PR 2 per-key reference
  makes over a cold sweep (one ``ProviderCache.fetch`` per unique cold
  key, the per-object interpreter loop in disguise);
- ``batched_round_trips`` — transport sends the batched lane makes for
  the same sweep (the deduped miss list, ``max_keys_per_call`` per
  send);
- ``dedupe_ratio``        — per-key / batched round-trips (the headline:
  >= 10x at chunk >= 64 per the PR 11 acceptance bar);
- ``warm_round_trips``    — transport sends of a SECOND identical sweep
  over the resident columns (the steady-state number: 0);
- ``batched_sweep_s`` / ``perkey_sweep_s`` — wall time of the device
  sweep vs the interpreter reference at a simulated per-send transport
  latency (``--rtt-ms``, default 0 so CI smoke stays fast).

Appends the previous latest record to the ``history`` list in
``EXTDATA_BENCH.json`` (the FLATTEN_BENCH convention); ``host_cpus``
recorded because the flatten half scales with cores.  Run:

    python tools/bench_extdata.py [n_objects] [chunk_size]

``--smoke`` (tiny corpus, no file write unless --write) runs in the
slow lane via tests/test_extdata_bench.py so the script cannot rot.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TARGET = "admission.k8s.gatekeeper.sh"

RULES = """
package k8sextdata

violation[{"msg": msg}] {
  images := [img | img = input.review.object.spec.containers[_].image]
  response := external_data({"provider": "registry", "keys": images})
  count(response.errors) > 0
  msg := sprintf("invalid images: %v", [response.errors])
}
"""

MUTATOR = {
    "apiVersion": "mutations.gatekeeper.sh/v1",
    "kind": "Assign",
    "metadata": {"name": "pin-image"},
    "spec": {
        "applyTo": [{"groups": [""], "versions": ["v1"], "kinds": ["Pod"]}],
        "location": "spec.containers[name:*].image",
        "parameters": {"assign": {"externalData": {
            "provider": "registry", "dataSource": "ValueAtLocation",
            "failurePolicy": "Ignore"}}},
    },
}


class RegistryTransport:
    """Deterministic digest-registry double with a simulated RTT."""

    def __init__(self, rtt_s: float = 0.0):
        self.calls = 0
        self.keys = 0
        self.rtt_s = rtt_s

    def __call__(self, provider, keys):
        self.calls += 1
        self.keys += len(keys)
        if self.rtt_s:
            time.sleep(self.rtt_s)
        items = []
        for k in keys:
            if "forbidden" in k:
                items.append({"key": k, "error": "untrusted registry"})
            elif "@sha256:" in k:
                items.append({"key": k, "value": k})
            else:
                items.append({"key": k, "value": f"{k}@sha256:{hash(k) & 0xFFFF:04x}"})
        return {"response": {"items": items, "systemError": ""}}


def make_corpus(n: int, registry_size: int, seed: int = 7) -> list:
    import random

    rng = random.Random(seed)
    pods = []
    for i in range(n):
        containers = []
        for j in range(rng.randint(1, 3)):
            r = rng.randrange(registry_size)
            base = ("forbidden" if r % 11 == 0 else f"registry.example/app{r}")
            containers.append({"name": f"c{j}", "image": base})
        pods.append({"apiVersion": "v1", "kind": "Pod",
                     "metadata": {"name": f"p{i}", "uid": f"u{i}",
                                  "namespace": f"ns{i % 17}"},
                     "spec": {"containers": containers}})
    return pods


def run_bench(n_objects: int = 20_000, chunk_size: int = 2048,
              registry_size: int = 4096, rtt_ms: float = 0.0,
              max_keys_per_call: int = 256,
              out_path: str = None, write: bool = True) -> dict:
    from gatekeeper_tpu.apis.constraints import Constraint
    from gatekeeper_tpu.apis.templates import ConstraintTemplate
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.extdata import ExtDataLane, activate
    from gatekeeper_tpu.externaldata.providers import (Provider,
                                                       ProviderCache)
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh

    pods = make_corpus(n_objects, registry_size)
    unique_keys = sorted({c["image"] for p in pods
                          for c in p["spec"]["containers"]})

    def setup(mode):
        transport = RegistryTransport(rtt_s=rtt_ms / 1000.0)
        cache = ProviderCache(send_fn=transport)
        cache.upsert(Provider(name="registry", url="https://r",
                              ca_bundle="x"))
        lane = ExtDataLane(cache, mode=mode,
                           max_keys_per_call=max_keys_per_call)
        tpu = TpuDriver()
        tpu.extdata_lane = lane
        tpu.add_template(ConstraintTemplate.from_unstructured({
            "apiVersion": "templates.gatekeeper.sh/v1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": "k8sextdata"},
            "spec": {"crd": {"spec": {"names": {"kind": "K8sExtData"}}},
                     "targets": [{"target": TARGET, "rego": RULES}]}}))
        con = Constraint(kind="K8sExtData", name="registry-check",
                         match={}, parameters={},
                         enforcement_action="deny")
        tpu.add_constraint(con)
        return lane, transport, tpu, con

    def chunks():
        for i in range(0, len(pods), chunk_size):
            yield pods[i:i + chunk_size]

    # --- batched lane: device sweep, bulk calls ------------------------
    lane_b, tr_b, tpu_b, con_b = setup("batched")
    ev = ShardedEvaluator(tpu_b, make_mesh(), violations_limit=20)
    with activate(lane_b):
        t0 = time.perf_counter()
        total_b = 0
        for ch in chunks():
            out = ev.sweep([con_b], ch)
            if out:
                _cons, _idx, _valid, counts, _bits = out["K8sExtData"]
                total_b += int(counts.sum())
        batched_sweep_s = time.perf_counter() - t0
        batched_round_trips = tr_b.calls
        # warm steady state: the same sweep again over resident columns
        warm0 = tr_b.calls
        for ch in chunks():
            ev.sweep([con_b], ch)
        warm_round_trips = tr_b.calls - warm0

    # --- per-key reference: interpreter loop, one fetch per cold key ---
    lane_p, tr_p, tpu_p, con_p = setup("perkey")
    from gatekeeper_tpu.target.review import AugmentedUnstructured
    from gatekeeper_tpu.target.target import K8sValidationTarget

    target = K8sValidationTarget()
    with activate(lane_p):
        t0 = time.perf_counter()
        total_p = 0
        for p in pods:
            review = target.handle_review(AugmentedUnstructured(object=p))
            total_p += len(
                tpu_p._interp.query(TARGET, [con_p], review).results)
        perkey_sweep_s = time.perf_counter() - t0
        perkey_round_trips = tr_p.calls
    if total_b != total_p:
        raise AssertionError(
            f"lane verdict mismatch: batched {total_b} vs perkey {total_p}")

    # --- mutation-side consumer: one placeholder pass ------------------
    lane_m, tr_m, _tpu_m, _con = setup("batched")
    cache_m = lane_m.cache
    system = MutationSystem(provider_cache=cache_m)
    system.upsert_unstructured(MUTATOR)
    sample = [json.loads(json.dumps(p)) for p in pods[:chunk_size]]
    with activate(lane_m):
        t0 = time.perf_counter()
        for obj in sample:
            system.mutate(obj)
        mutate_s = time.perf_counter() - t0
        mutate_round_trips = tr_m.calls

    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cpus": os.cpu_count(),
        "n_objects": n_objects,
        "chunk_size": chunk_size,
        "unique_keys": len(unique_keys),
        "rtt_ms": rtt_ms,
        "max_keys_per_call": max_keys_per_call,
        "violations": total_b,
        "perkey_round_trips": perkey_round_trips,
        "batched_round_trips": batched_round_trips,
        "dedupe_ratio": round(perkey_round_trips
                              / max(1, batched_round_trips), 1),
        "warm_round_trips": warm_round_trips,
        "mutate_round_trips": mutate_round_trips,
        "batched_sweep_s": round(batched_sweep_s, 3),
        "perkey_sweep_s": round(perkey_sweep_s, 3),
    }
    if write:
        path = out_path or os.path.join(os.path.dirname(__file__), "..",
                                        "EXTDATA_BENCH.json")
        doc = {"history": []}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                doc = {"history": []}
            latest = {k: v for k, v in doc.items() if k != "history"}
            if latest:
                doc.setdefault("history", []).append(latest)
        history = doc.get("history", [])
        doc = dict(record)
        doc["history"] = history
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote {path}")
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--smoke" in argv:
        rec = run_bench(n_objects=300, chunk_size=64, registry_size=128,
                        write="--write" in argv)
        print(json.dumps(rec, indent=2))
        return 0
    pos = [a for a in argv if not a.startswith("--")]
    n = int(pos[0]) if pos else 20_000
    chunk = int(pos[1]) if len(pos) > 1 else 2048
    rtt = 0.0
    for a in argv:
        if a.startswith("--rtt-ms="):
            rtt = float(a.split("=", 1)[1])
    rec = run_bench(n_objects=n, chunk_size=chunk, rtt_ms=rtt)
    print(json.dumps(rec, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
