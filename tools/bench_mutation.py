"""Mutate-burst bench: batched lane vs host fixed-point loop.

Builds a representative mutator registry (lowered Assign/AssignMetadata
+ host-only ModifySet/assignIf fallbacks) over the synthetic cluster
corpus, then measures the two `/v1/mutate` serving shapes the ROADMAP's
L5 item cares about:

- ``host_objs_per_sec``    — the per-object reference path (the full
  fixed-point loop + RFC-6902 diff per object, what the pre-mutlane
  webhook did for every request);
- ``batched_objs_per_sec`` — the batched lane (one columnar classify
  pass per burst, patch columns for the supported fragment, host walk
  only on flagged objects).

A lane-outcome breakdown (noop/device/solo/host) and patch-op counts
ride along, plus a differential spot check (batched == reference on a
sample) so the bench can't report a number the correctness harness
would reject.  Appends the previous latest record to the ``history``
list in ``MUTATION_BENCH.json`` (the FLATTEN_BENCH convention);
``host_cpus`` is recorded because the columnize pass scales with cores.

    python tools/bench_mutation.py [n_objects] [burst_size]

``--smoke`` (tiny corpus, no file write unless asked) runs in the slow
test lane via tests/test_mutlane.py so the script cannot rot.
"""

from __future__ import annotations

import copy
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def make_mutators():
    """A representative registry: 6 lowered + 2 host-only mutators."""
    def assign(name, location, value, extra=None, kinds=("Pod",)):
        params = {"assign": {"value": value}}
        params.update(extra or {})
        return {
            "apiVersion": "mutations.gatekeeper.sh/v1",
            "kind": "Assign", "metadata": {"name": name},
            "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                                  "kinds": list(kinds)}],
                     "location": location, "parameters": params},
        }

    def assign_meta(name, location, value):
        return {
            "apiVersion": "mutations.gatekeeper.sh/v1beta1",
            "kind": "AssignMetadata", "metadata": {"name": name},
            "spec": {"location": location,
                     "parameters": {"assign": {"value": value}}},
        }

    return [
        assign("pull-policy",
               "spec.containers[name: *].imagePullPolicy", "Always"),
        assign("host-network", "spec.hostNetwork", False),
        assign("run-as-nonroot",
               "spec.securityContext.runAsNonRoot", True),
        assign("priority", "spec.priority", 100),
        assign_meta("owner-label", "metadata.labels.owner",
                    "platform-team"),
        assign_meta("audit-ann", "metadata.annotations.audited", "true"),
        # host-only: ModifySet and assignIf are outside the lowered
        # fragment — they exercise the mixed-batch fallback path
        {
            "apiVersion": "mutations.gatekeeper.sh/v1",
            "kind": "ModifySet", "metadata": {"name": "dns-opts"},
            "spec": {"applyTo": [{"groups": [""], "versions": ["v1"],
                                  "kinds": ["Service"]}],
                     "location": "spec.topologyKeys",
                     "parameters": {"operation": "merge",
                                    "values": {"fromList": ["zone"]}}},
        },
        assign("dns-policy-cond", "spec.dnsPolicy", "ClusterFirst",
               extra={"assignIf": {"in": ["Default"]}}),
    ]


def run_bench(n_objects: int = 5000, burst_size: int = 64,
              passes: int = 3, seed: int = 11, out_path: str = None,
              write: bool = True) -> dict:
    from gatekeeper_tpu.mutation.system import MutationSystem
    from gatekeeper_tpu.mutlane import MutationLane
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    system = MutationSystem()
    for m in make_mutators():
        system.upsert_unstructured(m)
    lane = MutationLane(system)
    objects = make_cluster_objects(n_objects, seed=seed)

    # differential spot check FIRST: the number is worthless if the lane
    # diverges (full-corpus equality is tier-1's job; a sample here)
    sample = objects[:: max(1, n_objects // 200)]
    for obj, out in zip(sample, lane.mutate_objects(
            sample, want_objects=True)):
        ref = lane.reference_outcome(obj)
        assert out.patch == ref.patch and (out.error is None) == (
            ref.error is None), "bench aborted: lane differential failed"

    # --- host loop (the per-object reference path) ----------------------
    host_n = min(n_objects, 2000)  # the slow side; bound the wall time
    t0 = time.perf_counter()
    for obj in objects[:host_n]:
        try:
            system.mutate(copy.deepcopy(obj))
        except Exception:
            pass
    host_s = time.perf_counter() - t0
    host_ops = host_n / host_s if host_s else 0.0

    # --- batched lane, burst-shaped (the webhook coalesce size) ---------
    def burst_pass(corpus):
        lanes: dict = {}
        patch_ops = 0
        t0 = time.perf_counter()
        for i in range(0, len(corpus), burst_size):
            for out in lane.mutate_objects(corpus[i:i + burst_size]):
                lanes[out.lane] = lanes.get(out.lane, 0) + 1
                patch_ops += len(out.patch or ())
        return time.perf_counter() - t0, lanes, patch_ops

    lane.mutate_objects(objects[:burst_size])  # compile + jit warmup
    best = None
    lanes: dict = {}
    patch_ops = 0
    for _ in range(passes):
        dt, lanes, patch_ops = burst_pass(objects)
        best = dt if best is None else min(best, dt)
    batched_ops = len(objects) / best if best else 0.0

    # --- steady state: the converged corpus (webhook reality — most
    # admissions arrive already mutated; the noop fast path answers
    # without a deepcopy or walk) ---------------------------------------
    converged = [o.obj for o in lane.mutate_objects(
        objects, want_objects=True)]
    t0 = time.perf_counter()
    for obj in converged[:host_n]:
        try:
            system.mutate(copy.deepcopy(obj))
        except Exception:
            pass
    steady_host_s = time.perf_counter() - t0
    steady_host_ops = host_n / steady_host_s if steady_host_s else 0.0
    best_s = None
    steady_lanes: dict = {}
    for _ in range(passes):
        dt, steady_lanes, _ops = burst_pass(converged)
        best_s = dt if best_s is None else min(best_s, dt)
    steady_batched_ops = len(converged) / best_s if best_s else 0.0

    record = {
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cpus": os.cpu_count(),
        "n_objects": n_objects,
        "burst_size": burst_size,
        "n_mutators": len(system.mutators()),
        "lowered_mutators": len(lane.compiled().lowered),
        "host_only_mutators": len(lane.compiled().host_only),
        "host_objs_per_sec": round(host_ops, 1),
        "batched_objs_per_sec": round(batched_ops, 1),
        "speedup": round(batched_ops / host_ops, 2) if host_ops else 0.0,
        "lanes": lanes,
        "patch_ops": patch_ops,
        "steady_host_objs_per_sec": round(steady_host_ops, 1),
        "steady_batched_objs_per_sec": round(steady_batched_ops, 1),
        "steady_speedup": round(steady_batched_ops / steady_host_ops, 2)
        if steady_host_ops else 0.0,
        "steady_lanes": steady_lanes,
    }
    if write:
        path = out_path or os.path.join(os.path.dirname(__file__), "..",
                                        "MUTATION_BENCH.json")
        doc = {"history": []}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                doc = {"history": []}
            latest = {k: v for k, v in doc.items() if k != "history"}
            if latest:
                doc.setdefault("history", []).append(latest)
        history = doc.get("history", [])
        doc = dict(record)
        doc["history"] = history
        with open(path, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote {path}")
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--smoke" in argv:
        rec = run_bench(n_objects=200, burst_size=32, passes=1,
                        write="--write" in argv)
        print(json.dumps(rec, indent=2))
        return 0
    n = int(argv[0]) if argv else 5000
    burst = int(argv[1]) if len(argv) > 1 else 64
    rec = run_bench(n_objects=n, burst_size=burst)
    print(json.dumps(rec, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
