"""Observability registry lint.

Cross-checks the code's observability surface against the documented
registry (``tools/observability_registry.md``):

- every ``fault_point("<site>")`` call site in ``gatekeeper_tpu/`` must
  be documented (f-string sites like ``pipeline.stage.{name}`` are
  normalized to their ``pipeline.stage.*`` pattern);
- every metric-name constant in ``gatekeeper_tpu/metrics/registry.py``
  must be documented under its exposed ``gatekeeper_*`` name;
- stale documentation (a documented site/metric that no longer exists
  in the source) fails too, so the registry can be trusted.

Run standalone (``python tools/lint_observability.py``) or via tier-1
(``tests/test_observability_lint.py``).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "gatekeeper_tpu"
REGISTRY_MD = REPO / "tools" / "observability_registry.md"
METRICS_PY = PKG / "metrics" / "registry.py"

_FAULT_CALL = re.compile(r'fault_point\(\s*(f?)"([^"]+)"')
_DOC_ENTRY = re.compile(r"^\s*-\s+`([^`]+)`")
_FSTRING_FIELD = re.compile(r"\{[^}]*\}")


def documented() -> tuple[set, set]:
    """(fault sites, metric names) parsed from the registry markdown."""
    sites: set = set()
    metrics: set = set()
    section = ""
    for line in REGISTRY_MD.read_text().splitlines():
        if line.startswith("## "):
            section = line[3:].strip().lower()
            continue
        m = _DOC_ENTRY.match(line)
        if not m:
            continue
        if section.startswith("fault sites"):
            sites.add(m.group(1))
        elif section.startswith("metrics"):
            metrics.add(m.group(1))
    return sites, metrics


def fault_sites_in_source() -> dict:
    """site -> [file:line] for every ``fault_point("...")`` literal in
    the package (docstrings included — a documented example must name a
    real site too).  F-string sites normalize ``{expr}`` to ``*``."""
    out: dict = {}
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        # whole-text scan: call sites wrap across lines (the \s* spans
        # the newline between the paren and the site string)
        for m in _FAULT_CALL.finditer(text):
            site = m.group(2)
            if m.group(1):  # f-string: dynamic segments become *
                site = _FSTRING_FIELD.sub("*", site)
            line = text.count("\n", 0, m.start()) + 1
            out.setdefault(site, []).append(
                f"{path.relative_to(REPO)}:{line}")
    return out


def metric_names_in_source() -> dict:
    """exposed name ('gatekeeper_' + value) -> constant name, from the
    module-level string constants of metrics/registry.py."""
    tree = ast.parse(METRICS_PY.read_text())
    prefix = "gatekeeper_"
    out: dict = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) or not target.id.isupper():
            continue
        if target.id == "PREFIX":
            if isinstance(node.value, ast.Constant):
                prefix = node.value.value
            continue
        if isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            out[prefix + node.value.value] = target.id
    return out


def check() -> list:
    """List of problem strings; empty means the registry is in sync."""
    problems: list = []
    doc_sites, doc_metrics = documented()
    src_sites = fault_sites_in_source()
    src_metrics = metric_names_in_source()
    for site, where in sorted(src_sites.items()):
        if site not in doc_sites:
            problems.append(
                f"undocumented fault site {site!r} ({where[0]}) — add it "
                f"to {REGISTRY_MD.relative_to(REPO)}")
    for site in sorted(doc_sites - set(src_sites)):
        problems.append(
            f"stale documented fault site {site!r} — no fault_point() "
            "call site matches; remove it from the registry")
    for name, const in sorted(src_metrics.items()):
        if name not in doc_metrics:
            problems.append(
                f"undocumented metric {name!r} (constant {const} in "
                f"{METRICS_PY.relative_to(REPO)}) — add it to "
                f"{REGISTRY_MD.relative_to(REPO)}")
    for name in sorted(doc_metrics - set(src_metrics)):
        problems.append(
            f"stale documented metric {name!r} — no matching constant in "
            f"{METRICS_PY.relative_to(REPO)}; remove it from the registry")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"lint: {p}", file=sys.stderr)
    if not problems:
        sites, metrics = documented()
        print(f"observability registry in sync: {len(sites)} fault "
              f"sites, {len(metrics)} metrics")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
