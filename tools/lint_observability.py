"""Observability registry lint.

Cross-checks the code's observability surface against the documented
registry (``tools/observability_registry.md``):

- every ``fault_point("<site>")`` call site in ``gatekeeper_tpu/`` must
  be documented (f-string sites like ``pipeline.stage.{name}`` are
  normalized to their ``pipeline.stage.*`` pattern);
- every metric-name constant in ``gatekeeper_tpu/metrics/registry.py``
  must be documented under its exposed ``gatekeeper_*`` name;
- every tracer span name (``span("...")`` call sites) must be
  documented — the trace timeline is an API surface too;
- every built-in SLO objective name
  (``observability/slo.py:DEFAULT_OBJECTIVES``) must be documented —
  dashboards key on ``gatekeeper_slo_*{objective=...}`` values;
- every built-in degradation action
  (``resilience/overload.py:BUILTIN_ACTIONS``) must be documented —
  SLO degradation maps and ``--slo-config`` files name them, and
  ``gatekeeper_slo_degradation_active{action=...}`` keys on them;
- every ``/debug/*`` endpoint constant in ``webhook/server.py``
  (``*_PATH = "/debug/..."``) must be documented — runbooks and
  ``gator triage`` depend on those paths existing;
- stale documentation (a documented site/metric/span/objective/
  endpoint that no longer exists in the source) fails too, so the
  registry can be trusted.

Run standalone (``python tools/lint_observability.py``) or via tier-1
(``tests/test_observability_lint.py``).
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
PKG = REPO / "gatekeeper_tpu"
REGISTRY_MD = REPO / "tools" / "observability_registry.md"
METRICS_PY = PKG / "metrics" / "registry.py"
SLO_PY = PKG / "observability" / "slo.py"
SHADOW_PY = PKG / "replay" / "shadow.py"
SERVER_PY = PKG / "webhook" / "server.py"
OVERLOAD_PY = PKG / "resilience" / "overload.py"

_FAULT_CALL = re.compile(r'fault_point\(\s*(f?)"([^"]+)"')
# tracer span call sites: tracing.span("..."), otel.span("..."),
# tracer.start_span("...") — the \s* spans a line wrap after the paren
_SPAN_CALL = re.compile(r'\b(?:span|start_span)\(\s*(f?)"([^"]+)"')
_DOC_ENTRY = re.compile(r"^\s*-\s+`([^`]+)`")
_FSTRING_FIELD = re.compile(r"\{[^}]*\}")
# route constants at the top of webhook/server.py; only the /debug/*
# surface is registry-checked (the serving paths are API, not debug)
_ENDPOINT_CONST = re.compile(
    r'^([A-Z][A-Z0-9_]*_PATH)\s*=\s*"(/debug/[^"]*)"', re.M)


def documented() -> tuple[set, set, set, set]:
    """(fault sites, metric names, span names, slo objectives) parsed
    from the registry markdown."""
    sites: set = set()
    metrics: set = set()
    spans: set = set()
    objectives: set = set()
    section = ""
    for line in REGISTRY_MD.read_text().splitlines():
        if line.startswith("## "):
            section = line[3:].strip().lower()
            continue
        m = _DOC_ENTRY.match(line)
        if not m:
            continue
        if section.startswith("fault sites"):
            sites.add(m.group(1))
        elif section.startswith("metrics"):
            metrics.add(m.group(1))
        elif section.startswith("spans"):
            spans.add(m.group(1))
        elif section.startswith("slo objectives"):
            objectives.add(m.group(1))
    return sites, metrics, spans, objectives


def documented_endpoints() -> set:
    """Debug endpoint paths parsed from the registry markdown's
    ``## Debug endpoints`` section (kept apart from :func:`documented`
    so its 4-tuple shape stays stable for callers)."""
    endpoints: set = set()
    section = ""
    for line in REGISTRY_MD.read_text().splitlines():
        if line.startswith("## "):
            section = line[3:].strip().lower()
            continue
        m = _DOC_ENTRY.match(line)
        if m and section.startswith("debug endpoints"):
            endpoints.add(m.group(1))
    return endpoints


def documented_actions() -> set:
    """Degradation action names parsed from the registry markdown's
    ``## Degradation actions`` section (kept apart from
    :func:`documented` so its 4-tuple shape stays stable)."""
    actions: set = set()
    section = ""
    for line in REGISTRY_MD.read_text().splitlines():
        if line.startswith("## "):
            section = line[3:].strip().lower()
            continue
        m = _DOC_ENTRY.match(line)
        if m and section.startswith("degradation actions"):
            actions.add(m.group(1))
    return actions


def degradation_actions_in_source() -> dict:
    """action name -> defining constant, from the
    ``BUILTIN_ACTIONS`` dict of resilience/overload.py.  Keys are
    module-constant references (``NS_CACHE_STALE``), so constant
    assignments resolve first; a literal string key works too."""
    tree = ast.parse(OVERLOAD_PY.read_text())
    consts: dict = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant) \
                and isinstance(node.value.value, str):
            consts[node.targets[0].id] = node.value.value
    out: dict = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) \
                or target.id != "BUILTIN_ACTIONS" \
                or not isinstance(node.value, ast.Dict):
            continue
        for k in node.value.keys:
            if isinstance(k, ast.Name) and k.id in consts:
                out[consts[k.id]] = k.id
            elif isinstance(k, ast.Constant) \
                    and isinstance(k.value, str):
                out[k.value] = "<literal>"
    return out


def debug_endpoints_in_source() -> dict:
    """path -> constant name for every ``*_PATH = "/debug/..."`` route
    constant in webhook/server.py — the surface ``gator triage``
    snapshots and runbooks link to."""
    return {m.group(2): m.group(1)
            for m in _ENDPOINT_CONST.finditer(SERVER_PY.read_text())}


def fault_sites_in_source() -> dict:
    """site -> [file:line] for every ``fault_point("...")`` literal in
    the package (docstrings included — a documented example must name a
    real site too).  F-string sites normalize ``{expr}`` to ``*``."""
    out: dict = {}
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        # whole-text scan: call sites wrap across lines (the \s* spans
        # the newline between the paren and the site string)
        for m in _FAULT_CALL.finditer(text):
            site = m.group(2)
            if m.group(1):  # f-string: dynamic segments become *
                site = _FSTRING_FIELD.sub("*", site)
            line = text.count("\n", 0, m.start()) + 1
            out.setdefault(site, []).append(
                f"{path.relative_to(REPO)}:{line}")
    return out


def span_names_in_source() -> dict:
    """span name -> [file:line] for every ``span("...")`` /
    ``start_span("...")`` literal in the package.  F-string names
    (``pipeline.stage.{name}``) normalize their dynamic segments to
    ``*`` patterns, like fault sites."""
    out: dict = {}
    for path in sorted(PKG.rglob("*.py")):
        text = path.read_text()
        for m in _SPAN_CALL.finditer(text):
            name = m.group(2)
            if m.group(1):
                name = _FSTRING_FIELD.sub("*", name)
            line = text.count("\n", 0, m.start()) + 1
            out.setdefault(name, []).append(
                f"{path.relative_to(REPO)}:{line}")
    return out


def _objective_names(node) -> list:
    """``name`` values from an objective literal: a list of dicts
    (DEFAULT_OBJECTIVES) or one bare dict (SHADOW_OBJECTIVE)."""
    dicts = node.elts if isinstance(node, ast.List) else [node]
    names: list = []
    for elt in dicts:
        if not isinstance(elt, ast.Dict):
            continue
        for k, v in zip(elt.keys, elt.values):
            if isinstance(k, ast.Constant) and k.value == "name" \
                    and isinstance(v, ast.Constant):
                names.append(v.value)
    return names


def slo_objectives_in_source() -> dict:
    """objective name -> defining file, for every entry of
    ``slo.py:DEFAULT_OBJECTIVES`` plus opt-in objectives other modules
    define as module-level literals (``replay/shadow.py:
    SHADOW_OBJECTIVE``) — the names are the values dashboards and the
    breach counter key on."""
    out: dict = {}
    for path, wanted in ((SLO_PY, "DEFAULT_OBJECTIVES"),
                         (SHADOW_PY, "SHADOW_OBJECTIVE")):
        if not path.exists():
            continue
        for node in ast.parse(path.read_text()).body:
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            target = node.targets[0]
            if not isinstance(target, ast.Name) or target.id != wanted:
                continue
            for name in _objective_names(node.value):
                out[name] = str(path.relative_to(REPO))
    return out


def metric_names_in_source() -> dict:
    """exposed name ('gatekeeper_' + value) -> constant name, from the
    module-level string constants of metrics/registry.py."""
    tree = ast.parse(METRICS_PY.read_text())
    prefix = "gatekeeper_"
    out: dict = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) or not target.id.isupper():
            continue
        if target.id == "PREFIX":
            if isinstance(node.value, ast.Constant):
                prefix = node.value.value
            continue
        if isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str) and \
                re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", node.value.value):
            # shape filter: module constants that aren't metric names
            # (content-type strings etc.) don't belong in the registry
            out[prefix + node.value.value] = target.id
    return out


def check() -> list:
    """List of problem strings; empty means the registry is in sync."""
    problems: list = []
    doc_sites, doc_metrics, doc_spans, doc_slo = documented()
    src_sites = fault_sites_in_source()
    src_metrics = metric_names_in_source()
    src_spans = span_names_in_source()
    src_slo = slo_objectives_in_source()
    for site, where in sorted(src_sites.items()):
        if site not in doc_sites:
            problems.append(
                f"undocumented fault site {site!r} ({where[0]}) — add it "
                f"to {REGISTRY_MD.relative_to(REPO)}")
    for site in sorted(doc_sites - set(src_sites)):
        problems.append(
            f"stale documented fault site {site!r} — no fault_point() "
            "call site matches; remove it from the registry")
    for name, const in sorted(src_metrics.items()):
        if name not in doc_metrics:
            problems.append(
                f"undocumented metric {name!r} (constant {const} in "
                f"{METRICS_PY.relative_to(REPO)}) — add it to "
                f"{REGISTRY_MD.relative_to(REPO)}")
    for name in sorted(doc_metrics - set(src_metrics)):
        problems.append(
            f"stale documented metric {name!r} — no matching constant in "
            f"{METRICS_PY.relative_to(REPO)}; remove it from the registry")
    for name, where in sorted(src_spans.items()):
        if name not in doc_spans:
            problems.append(
                f"undocumented span name {name!r} ({where[0]}) — add it "
                f"to {REGISTRY_MD.relative_to(REPO)}")
    for name in sorted(doc_spans - set(src_spans)):
        problems.append(
            f"stale documented span name {name!r} — no span() call site "
            "matches; remove it from the registry")
    for name, where in sorted(src_slo.items()):
        if name not in doc_slo:
            problems.append(
                f"undocumented SLO objective {name!r} ({where}) — add it "
                f"to {REGISTRY_MD.relative_to(REPO)}")
    for name in sorted(doc_slo - set(src_slo)):
        problems.append(
            f"stale documented SLO objective {name!r} — not in "
            f"{SLO_PY.relative_to(REPO)}:DEFAULT_OBJECTIVES or "
            f"{SHADOW_PY.relative_to(REPO)}:SHADOW_OBJECTIVE; remove it "
            "from the registry")
    doc_actions = documented_actions()
    src_actions = degradation_actions_in_source()
    for name, const in sorted(src_actions.items()):
        if name not in doc_actions:
            problems.append(
                f"undocumented degradation action {name!r} (constant "
                f"{const} in {OVERLOAD_PY.relative_to(REPO)}:"
                f"BUILTIN_ACTIONS) — add it to "
                f"{REGISTRY_MD.relative_to(REPO)}")
    for name in sorted(doc_actions - set(src_actions)):
        problems.append(
            f"stale documented degradation action {name!r} — not in "
            f"{OVERLOAD_PY.relative_to(REPO)}:BUILTIN_ACTIONS; remove "
            "it from the registry")
    doc_endpoints = documented_endpoints()
    src_endpoints = debug_endpoints_in_source()
    for path, const in sorted(src_endpoints.items()):
        if path not in doc_endpoints:
            problems.append(
                f"undocumented debug endpoint {path!r} (constant {const} "
                f"in {SERVER_PY.relative_to(REPO)}) — add it to "
                f"{REGISTRY_MD.relative_to(REPO)}")
    for path in sorted(doc_endpoints - set(src_endpoints)):
        problems.append(
            f"stale documented debug endpoint {path!r} — no *_PATH "
            f"constant in {SERVER_PY.relative_to(REPO)} matches; remove "
            "it from the registry")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"lint: {p}", file=sys.stderr)
    if not problems:
        sites, metrics, spans, slo = documented()
        print(f"observability registry in sync: {len(sites)} fault "
              f"sites, {len(metrics)} metrics, {len(spans)} spans, "
              f"{len(slo)} SLO objectives, "
              f"{len(documented_actions())} degradation actions, "
              f"{len(documented_endpoints())} debug endpoints")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
