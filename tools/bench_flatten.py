"""Host-flatten throughput benchmark: dict lane vs threaded JSON lane.

The audit sweep's host-side ceiling is flatten throughput (VERDICT r2:
~15µs/object single-core ≈ 65k objects/s < the 100k reviews/s/chip
target).  This tool measures the shipped library's union flatten schema
over synthetic cluster objects on:
  - the Python flattener (oracle)
  - the C dict columnizer (flattenmod.c, GIL-bound)
  - the sweep entry point (Flattener.flatten, lane=auto — what
    sweep_flatten actually calls on RawJSON input)
  - the threaded JSON columnizer (flattenjsonmod.c) at 1..N threads;
    multi-thread lanes are skipped on one-core hosts (r05 showed
    1T==8T at host_cpus=1 — the numbers would be noise, not signal)

Writes FLATTEN_BENCH.json at the repo root: the latest capture at the
top level plus a ``history`` list (prior captures preserved), each
entry carrying host_cpus and per-lane thread counts.

Usage: python tools/bench_flatten.py [n_objects]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(n: int = 100_000):
    from gatekeeper_tpu.ops.flatten import Flattener, Schema, Vocab
    from gatekeeper_tpu.utils.rawjson import as_raw
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    import bench

    client, tpu, nt, nc = bench.build_client()
    schema = Schema()
    for kind in tpu.lowered_kinds():
        schema.merge(tpu._programs[kind].program.schema)
    n_cols = (len(schema.scalars) + len(schema.raggeds) +
              len(schema.keysets) + len(schema.ragged_keysets) +
              len(schema.map_keys) + len(schema.parent_idx))
    print(f"library: {nt} templates; union schema: {n_cols} columns, "
          f"{len(schema.axes())} axes")

    print(f"generating {n} objects...")
    objects = make_cluster_objects(n)
    raws = [as_raw(o) for o in objects]
    payload = sum(len(r.raw) for r in raws)
    print(f"payload: {payload / 1e6:.1f} MB JSON "
          f"({payload / max(1, n):.0f} B/object)")

    host_cpus = os.cpu_count() or 1
    chunk = 32_768
    results = {}

    def run(label, flatten_fn, threads=None, repeats=2):
        # warmup (page cache / allocator); then best-of-repeats
        flatten_fn(0, min(n, 2 * chunk))
        best = None
        for _ in range(repeats):
            t0 = time.perf_counter()
            for lo in range(0, n, chunk):
                flatten_fn(lo, min(n, lo + chunk))
            dt = time.perf_counter() - t0
            best = dt if best is None or dt < best else best
        rate = n / best
        us = 1e6 * best / n
        results[label] = {"objects_per_s": round(rate),
                          "us_per_object": round(us, 2),
                          "seconds": round(best, 3)}
        if threads is not None:
            results[label]["threads"] = threads
        print(f"{label:28s} {rate:10.0f} obj/s   {us:6.2f} µs/obj")

    # Python oracle (sampled at 1/10 scale: it is far too slow at n)
    sample = objects[: max(1, n // 10)]
    v = Vocab()
    f = Flattener(schema, v, use_native=False)
    t0 = time.perf_counter()
    for lo in range(0, len(sample), chunk):
        f.flatten(sample[lo:lo + chunk], pad_n=None)
    dt = time.perf_counter() - t0
    results["python"] = {"objects_per_s": round(len(sample) / dt),
                         "us_per_object": round(1e6 * dt / len(sample), 2),
                         "seconds": round(dt, 3),
                         "sampled_n": len(sample)}
    print(f"{'python (oracle, 1/10 n)':28s} {len(sample) / dt:10.0f} obj/s"
          f"   {1e6 * dt / len(sample):6.2f} µs/obj")

    v = Vocab()
    f = Flattener(schema, v, use_native=True, lane="dict")
    run("c-dict (GIL-bound)",
        lambda lo, hi: f.flatten(objects[lo:hi], pad_n=None), threads=1)

    # the sweep entry point: exactly what sweep_flatten calls (auto lane
    # routes RawJSON batches to the threaded raw columnizer)
    os.environ["GTPU_FLATTEN_THREADS"] = "0"
    v = Vocab()
    f = Flattener(schema, v, use_native=True, lane="auto")
    run(f"sweep-auto ({host_cpus}cpu)",
        lambda lo, hi: f.flatten(raws[lo:hi], pad_n=None),
        threads=host_cpus)

    # thread-count sweep of the raw lane: only where threads exist —
    # on a one-core host every lane measures the same single core
    thread_lanes = (1, 2, 4, 8, 0) if host_cpus >= 2 else (1,)
    if host_cpus < 2:
        print("host_cpus < 2: skipping multi-thread lanes "
              "(1T == NT on one core)")
    for nt_ in thread_lanes:
        os.environ["GTPU_FLATTEN_THREADS"] = str(nt_)
        v = Vocab()
        f = Flattener(schema, v, use_native=True)
        label = (f"c-json {nt_}T" if nt_
                 else f"c-json auto ({host_cpus}cpu)")
        run(label, lambda lo, hi: f.flatten_raw(raws[lo:hi], pad_n=None),
            threads=nt_ or host_cpus)
    del os.environ["GTPU_FLATTEN_THREADS"]

    best = max(results.values(), key=lambda r: r["objects_per_s"])
    dict_rate = results["c-dict (GIL-bound)"]["objects_per_s"]
    sweep_key = f"sweep-auto ({host_cpus}cpu)"
    out = {
        "n_objects": n,
        "chunk": chunk,
        "templates": nt,
        "schema_columns": n_cols,
        "payload_mb": round(payload / 1e6, 1),
        "host_cpus": host_cpus,
        "date": time.strftime("%Y-%m-%d"),
        "lanes": results,
        "headline_objects_per_s": best["objects_per_s"],
        "sweep_raw_vs_dict": round(
            results[sweep_key]["objects_per_s"] / max(1, dict_rate), 2),
        "target_objects_per_s": 100_000,
        "vs_target": round(best["objects_per_s"] / 100_000, 2),
    }
    path = os.path.join(os.path.dirname(__file__), "..",
                        "FLATTEN_BENCH.json")
    history = []
    try:
        with open(path) as fh:
            prev = json.load(fh)
        history = prev.pop("history", [])
        history.append(prev)  # the previous latest becomes history
    except (OSError, ValueError):
        pass
    out["history"] = history
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)
    print(json.dumps({"metric": "host flatten throughput",
                      "value": best["objects_per_s"],
                      "unit": "objects/s",
                      "vs_baseline": out["vs_target"]}))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 100_000)
