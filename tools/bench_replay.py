"""Replay bench: the `gator replay` time machine, end to end.

The headline number for the replay subsystem (ROADMAP "policy time
machine"): a serving webhook stack records N admission decisions into a
capture-mode flight-recorder sink (the raw request rides each JSONL
line), then `gator replay`'s core re-evaluates the corpus against a
CANDIDATE template set and diffs verdicts.  Two lanes:

- **identical** — the candidate IS the serving library.  Pins the
  subsystem's three invariants: the verdict diff is EMPTY, the
  ``--differential`` check is bit-identical (decision + message + code
  per record), and the candidate loads with ZERO fresh lowerings (every
  template comes out of the shared on-disk CompileCache the serving
  stack populated — replay never pays compile wall).
- **modified** — the candidate drops one constraint that produced
  recorded denies, so the diff must attribute ``newly_allowed``
  divergences to exactly that constraint.  When the recorded corpus
  contains no denies the lane SKIPS with a recorded reason (the
  FLATTEN_BENCH skip convention) instead of asserting on noise.

Appends the previous latest record to the ``history`` list in
``REPLAY_BENCH.json`` (the FLEET_BENCH convention).  Run:

    python tools/bench_replay.py [--smoke] [--out PATH]

``--smoke`` (fewer requests, template subset) runs in tier-1 via
tests/test_replay.py so the bench script itself cannot rot; it pins
bit-identity and the zero-fresh-lowering claim.
"""

from __future__ import annotations

import copy
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_KEEP = 5  # template-subset library: bounded compile wall (1-core host)


def _library_docs(keep: int = _KEEP) -> list:
    """The first ``keep`` shipped library templates + their sample
    constraints, as unstructured docs (the `--candidate` input shape)."""
    from gatekeeper_tpu.utils.synthetic import library_dir
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    docs: list = []
    tpaths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))[:keep]
    for tpath in tpaths:
        docs.append(load_yaml_file(tpath)[0])
        cpath = os.path.join(os.path.dirname(tpath), "samples",
                             "constraint.yaml")
        if os.path.exists(cpath):
            docs.extend(load_yaml_file(cpath))
    return docs


def _admission_bodies(n: int, seed: int = 7) -> list:
    """AdmissionReview bodies over the synthetic cluster mix (the
    loadtest shape: CREATE of the object, a non-gatekeeper user)."""
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    bodies = []
    for i, obj in enumerate(make_cluster_objects(n, seed=seed)):
        api = obj.get("apiVersion", "v1")
        group, _, version = api.rpartition("/")
        meta = obj.get("metadata") or {}
        bodies.append({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {
                "uid": f"bench-{i:06d}",
                "kind": {"group": group, "version": version,
                         "kind": obj.get("kind", "")},
                "operation": "CREATE",
                "name": meta.get("name", "") or f"obj-{i}",
                "namespace": meta.get("namespace", "") or "",
                "userInfo": {"username": "bench@replay"},
                "object": obj,
            },
        })
    return bodies


def _serve_and_record(docs: list, bodies: list, sink_path: str,
                      cache_dir: str) -> dict:
    """The serving pass: a real ValidationHandler + capture-mode flight
    recorder answers every body; the sink becomes the replay corpus."""
    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.generation import CompileCache
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.gator import reader
    from gatekeeper_tpu.observability import flightrec
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel, compile_cache=CompileCache(cache_dir))
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    for doc in docs:
        if reader.is_template(doc):
            client.add_template(doc)
    for doc in docs:
        if reader.is_constraint(doc):
            client.add_constraint(doc)
    if getattr(tpu, "gen_coord", None) is not None:
        tpu.gen_coord.constraints_fn = client.constraints
    handler = ValidationHandler(client)
    rec = flightrec.FlightRecorder(capacity=64, sink_path=sink_path,
                                   capture=True)
    denies = 0
    t0 = time.perf_counter()
    with flightrec.activate(rec):
        for body in bodies:
            resp = handler.handle(body)
            denies += 0 if resp.allowed else 1
    wall = time.perf_counter() - t0
    rec.close()
    gc = getattr(tpu, "gen_coord", None)
    if gc is not None:
        gc.stop()
    return {"wall_s": round(wall, 3), "served": len(bodies),
            "denies": denies,
            "compile_cache": tpu._compile_cache.stats()}


def _replay_lane(records, docs: list, cache_dir: str,
                 differential: bool) -> dict:
    """One candidate replay pass over the corpus (a fresh runtime per
    lane: the zero-lowering claim is about the ON-DISK cache, not a
    shared in-process driver)."""
    from gatekeeper_tpu.replay import core

    runtime = core.load_candidate(docs, compile_cache_dir=cache_dir)
    try:
        report = core.replay_decisions(records, runtime,
                                       differential=differential)
    finally:
        gc = getattr(runtime.driver, "gen_coord", None)
        if gc is not None:
            gc.stop()
    return report


def run_bench(n_requests: int = 400, keep: int = _KEEP,
              out_path: str = None, write: bool = True,
              cache_dir: str = None) -> dict:
    """``cache_dir``: reuse a warm on-disk compile cache (the tier-1
    smoke shares the test module's, so the bench measures replay
    throughput instead of template lowering)."""
    import contextlib

    from gatekeeper_tpu.replay import core

    record = {
        "kind": "replay_bench",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cpus": os.cpu_count() or 1,
        "n_requests": n_requests,
        "templates_kept": keep,
    }
    docs = _library_docs(keep)
    bodies = _admission_bodies(n_requests)
    ctx = (contextlib.nullcontext(cache_dir) if cache_dir
           else tempfile.TemporaryDirectory(prefix="gtpu-replay-cc-"))
    with ctx as d, tempfile.TemporaryDirectory(
            prefix="gtpu-replay-corpus-") as cd:
        sink = os.path.join(cd, "decisions.jsonl")
        record["serve"] = _serve_and_record(docs, bodies, sink, d)
        records, counts = core.read_corpus(sink)
        record["corpus"] = {"records": len(records), **counts}

        ident = _replay_lane(records, docs, d, differential=True)
        cc = ident.get("compile_cache") or {}
        zero_lowerings = (cc.get("misses", -1) == 0
                          and cc.get("hits", 0) > 0)
        if not zero_lowerings:
            raise AssertionError(
                f"candidate replay paid fresh lowerings: {cc}")
        if ident["divergences_total"]:
            raise AssertionError(
                "identical candidate diverged: "
                f"{ident['divergences'][:3]}")
        if not ident["differential"]["bit_identical"]:
            raise AssertionError(
                "differential replay not bit-identical: "
                f"{ident['differential']}")
        record["identical"] = {
            "wall_s": ident["wall_s"],
            "decisions_per_s": ident["decisions_per_s"],
            "divergences_total": ident["divergences_total"],
            "differential": ident["differential"],
            "compile_cache": cc,
            "lowering": ident.get("lowering") or {},
        }

        # modified lane: drop the first constraint with recorded denies
        denied_cons = set()
        for r in records:
            if r.get("decision") == "deny":
                denied_cons.update(
                    core.recorded_constraints(r.get("message", "")))
        if denied_cons:
            from gatekeeper_tpu.gator import reader
            from gatekeeper_tpu.utils.unstructured import name_of

            drop = sorted(denied_cons)[0]
            mod_docs = [doc for doc in docs
                        if not (reader.is_constraint(doc)
                                and name_of(doc) == drop)]
            mod = _replay_lane(records, mod_docs, d, differential=False)
            per_con = (mod.get("by_constraint") or {}).get(drop) or {}
            record["modified"] = {
                "dropped_constraint": drop,
                "wall_s": mod["wall_s"],
                "decisions_per_s": mod["decisions_per_s"],
                "divergences_total": mod["divergences_total"],
                "newly_allowed": mod["newly_allowed"],
                "dropped_constraint_diff": per_con,
                "top_offenders": mod.get("top_offenders") or {},
            }
            if not mod["newly_allowed"]:
                raise AssertionError(
                    f"dropping {drop} produced no newly_allowed "
                    "divergences")
        else:
            record["modified"] = {
                "skipped": "corpus recorded zero denies; the drop-a-"
                           "constraint lane would assert on noise"}
        record["headline"] = {
            "decisions_per_s": ident["decisions_per_s"],
            "bit_identical": True,
            "zero_fresh_lowerings": True,
            "modified_divergences": record["modified"].get(
                "divergences_total", None),
        }
    if write:
        out = out_path or os.path.join(os.path.dirname(__file__), "..",
                                       "REPLAY_BENCH.json")
        history = []
        if os.path.exists(out):
            try:
                with open(out) as fh:
                    prev = json.load(fh)
                history = prev.pop("history", [])
                history.append(prev)  # previous latest becomes history
            except Exception:
                history = []
        record_out = dict(record)
        record_out["history"] = history
        with open(out, "w") as fh:
            json.dump(record_out, fh, indent=1)
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    smoke = "--smoke" in argv
    argv = [a for a in argv if a != "--smoke"]
    out = None
    if "--out" in argv:
        i = argv.index("--out")
        out = argv[i + 1]
        del argv[i: i + 2]
    if smoke:
        rec = run_bench(n_requests=120, out_path=out,
                        write=out is not None)
    else:
        rec = run_bench(out_path=out)
    print(json.dumps({"headline": rec["headline"],
                      "identical": rec["identical"],
                      "modified": rec["modified"]}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
