"""Fleet packing bench: K small clusters packed vs sequential.

The headline number for fleet mode (ROADMAP "one evaluator, N
clusters"): K clusters running the same template library, each too
small to fill a device batch, swept (a) SEQUENTIALLY — each cluster's
chunks dispatch alone, the N-independent-sweeps geometry — and (b)
PACKED — the fleet scheduler coalesces same-group chunks across
clusters into device-sized dispatches.  Verdicts are bit-identical by
construction (asserted here per cluster); the wins are the device
dispatch count (fixed per-dispatch costs: masks, wire pack,
device_put commands, jit call) and padding waste, both collapsing
~K-fold.  Also records the runtime-sharing story: every cluster past
the first attaches with zero fresh lowerings and zero fused retraces.

Appends the previous latest record to the ``history`` list in
``FLEET_BENCH.json`` (the FLATTEN_BENCH convention).  Run:

    python tools/bench_fleet.py [--smoke] [--out PATH]

``--smoke`` (fewer clusters/objects) runs in tier-1 via
tests/test_fleet.py so the bench script itself cannot rot; it pins the
dispatch-count reduction >= 2x at K=4.
"""

from __future__ import annotations

import copy
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_KEEP = 5  # template-subset library: bounded compile wall (1-core host)


def _all_kinds():
    from gatekeeper_tpu.utils.synthetic import library_dir
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    paths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))
    return [load_yaml_file(p)[0]["spec"]["crd"]["spec"]["names"]["kind"]
            for p in paths]


def _builder(cache_dir: str, skip, lower_counter=None):
    def build():
        from gatekeeper_tpu.apis.constraints import AUDIT_EP
        from gatekeeper_tpu.client.client import Client
        from gatekeeper_tpu.drivers.cel_driver import CELDriver
        from gatekeeper_tpu.drivers.generation import CompileCache
        from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
        from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                     make_mesh)
        from gatekeeper_tpu.target.target import K8sValidationTarget
        from gatekeeper_tpu.utils.synthetic import load_library

        cel = CELDriver()
        tpu = TpuDriver(cel_driver=cel,
                        compile_cache=CompileCache(cache_dir))
        client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                        enforcement_points=[AUDIT_EP])
        load_library(client, skip_kinds=skip)
        ev = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)
        return client, tpu, ev

    return build


def _make_fleet(k: int, n_objects: int, chunk: int, cache_dir: str,
                seed0: int = 11):
    """A K-cluster fleet over one shared library runtime."""
    from gatekeeper_tpu.fleet import FleetEvaluator
    from gatekeeper_tpu.sync.source import FakeCluster
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    skip = tuple(_all_kinds()[_KEEP:])
    fleet = FleetEvaluator(chunk_size=chunk, exact_totals=False)
    for i in range(k):
        src = FakeCluster()
        for o in make_cluster_objects(n_objects, seed=seed0 + i):
            src.apply(copy.deepcopy(o))
        fleet.add_cluster(f"c{i:02d}", src, "lib", _builder(cache_dir,
                                                            skip))
    return fleet


def _sweep_lane(fleet, pack: bool) -> dict:
    """One full fleet pass; every snapshot re-dirtied first so both
    lanes evaluate identical row sets."""
    rt = fleet.runtimes()[0]
    ev = rt.evaluator
    d0, t0c = ev.dispatch_count, ev.trace_count
    t0 = time.perf_counter()
    runs = fleet.sweep(full=True, pack=pack)
    wall = time.perf_counter() - t0
    return {
        "wall_s": round(wall, 3),
        "dispatches": ev.dispatch_count - d0,
        "traces": ev.trace_count - t0c,
        "runs": runs,
    }


def run_bench(k: int = 4, n_objects: int = 96, chunk: int = 500,
              out_path: str = None, write: bool = True,
              cache_dir: str = None) -> dict:
    """``cache_dir``: reuse a warm on-disk compile cache (the tier-1
    smoke shares the test module's, so the bench measures dispatch
    geometry instead of template lowering)."""
    import contextlib

    from gatekeeper_tpu.audit.manager import AuditManager

    record = {
        "kind": "fleet_bench",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cpus": os.cpu_count() or 1,
        "clusters": k,
        "objects_per_cluster": n_objects,
        "chunk_size": chunk,
    }
    ctx = (contextlib.nullcontext(cache_dir) if cache_dir
           else tempfile.TemporaryDirectory(prefix="gtpu-fleet-cc-"))
    with ctx as d:
        fleet = _make_fleet(k, n_objects, chunk, d)
        rt = fleet.runtimes()[0]
        record["library_runtimes"] = len(fleet.runtimes())
        record["shared_boots"] = fleet.shared_boots
        cc = rt.driver._compile_cache
        record["compile_cache"] = cc.stats() if cc is not None else {}

        # warm pass: land the packed and unpacked executables so the
        # timed lanes measure dispatch geometry, not jit compiles
        _sweep_lane(fleet, pack=True)
        for fc in fleet.clusters.values():
            for store, rows in fc.snapshot.all_rows().items():
                fc.snapshot._dirty.update(g for g, _p in rows)
        _sweep_lane(fleet, pack=False)

        lanes = {}
        ref_runs = None
        for name, pack in (("sequential", False), ("packed", True)):
            for fc in fleet.clusters.values():
                for store, rows in fc.snapshot.all_rows().items():
                    fc.snapshot._dirty.update(g for g, _p in rows)
            lane = _sweep_lane(fleet, pack=pack)
            runs = lane.pop("runs")
            if ref_runs is None:
                ref_runs = runs
            else:
                for cid, run in runs.items():
                    ref = ref_runs[cid]
                    diff = AuditManager._verdicts_differ_canonical(
                        run.kept, run.total_violations,
                        ref.kept, ref.total_violations, 20)
                    if diff is not None:
                        raise AssertionError(
                            f"packed != sequential for {cid}: {diff}")
            lane["violations"] = sum(
                sum(r.total_violations.values()) for r in runs.values())
            lanes[name] = lane
        record["lanes"] = lanes
        seq, packed = lanes["sequential"], lanes["packed"]
        record["headline"] = {
            "dispatch_reduction": round(
                seq["dispatches"] / max(1, packed["dispatches"]), 2),
            "wall_ratio": round(
                packed["wall_s"] / seq["wall_s"], 3)
            if seq["wall_s"] else None,
            "verdicts_bit_identical": True,
            "second_cluster_zero_lowering": fleet.shared_boots == k - 1,
        }
        fleet.stop()
    if write:
        out = out_path or os.path.join(os.path.dirname(__file__), "..",
                                       "FLEET_BENCH.json")
        history = []
        if os.path.exists(out):
            try:
                with open(out) as fh:
                    prev = json.load(fh)
                history = prev.pop("history", [])
                history.append(prev)  # previous latest becomes history
            except Exception:
                history = []
        record_out = dict(record)
        record_out["history"] = history
        with open(out, "w") as fh:
            json.dump(record_out, fh, indent=1)
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    smoke = "--smoke" in argv
    argv = [a for a in argv if a != "--smoke"]
    out = None
    if "--out" in argv:
        i = argv.index("--out")
        out = argv[i + 1]
        del argv[i: i + 2]
    if smoke:
        rec = run_bench(k=4, n_objects=40, out_path=out,
                        write=out is not None)
    else:
        rec = run_bench(out_path=out)
    print(json.dumps({"headline": rec["headline"],
                      "lanes": rec["lanes"],
                      "shared_boots": rec["shared_boots"]}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
