"""Webhook serving-layer load test (VERDICT r1 #10).

Drives the real WebhookServer (TLS off) with concurrent AdmissionReview
POSTs over persistent connections, through the full stack: HTTP parse →
ValidationHandler → Batcher microbatch lane → device verdict grids →
deny/warn partition.  Reports throughput + a latency histogram and writes
WEBHOOK_LOAD.json at the repo root.

    JAX_PLATFORMS=cpu python tools/loadtest_webhook.py [n_requests] [conc]

The reference's concurrency model is goroutine-per-request capped by
--max-serving-threads (pkg/webhook/policy.go:116-120); here the cap is the
batch window — see the batch-size distribution in the output.
"""

from __future__ import annotations

import http.client
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_server():
    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import load_library
    from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
    from gatekeeper_tpu.webhook.server import WebhookServer

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    nt, nc = load_library(client)
    batcher = Batcher(client, window_s=0.002, max_batch=64).start()
    handler = ValidationHandler(client, batcher=batcher)
    srv = WebhookServer(validation_handler=handler, port=0,
                        readiness_check=lambda: True).start()
    return srv, batcher, nt, nc


def make_body(i: int) -> bytes:
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    obj = make_cluster_objects(1, seed=i)[0]
    from gatekeeper_tpu.utils.unstructured import gvk_of

    g, v, k = gvk_of(obj)
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": f"u{i}", "operation": "CREATE",
            "kind": {"group": g, "version": v, "kind": k},
            "name": obj["metadata"].get("name", ""),
            "namespace": obj["metadata"].get("namespace", ""),
            "userInfo": {"username": "load"},
            "object": obj,
        },
    }).encode()


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    conc = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    srv, batcher, nt, nc = build_server()
    print(f"server on :{srv.port}; library {nt} templates / {nc} "
          f"constraints; {n} requests x {conc} connections",
          file=sys.stderr)
    bodies = [make_body(i) for i in range(min(n, 256))]

    # warmup (jit compile of the batch shapes)
    conn = http.client.HTTPConnection("127.0.0.1", srv.port)
    for i in range(8):
        conn.request("POST", "/v1/admit", body=bodies[i % len(bodies)],
                     headers={"Content-Type": "application/json"})
        conn.getresponse().read()
    conn.close()

    latencies: list = []
    denied = [0]
    lock = threading.Lock()
    per_worker = n // conc

    errors: list = []

    def worker(wid: int):
        # persistent connection per worker (connection reuse)
        c = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=60)
        local = []
        local_denied = 0
        try:
            for i in range(per_worker):
                body = bodies[(wid * per_worker + i) % len(bodies)]
                t0 = time.perf_counter()
                c.request("POST", "/v1/admit", body=body,
                          headers={"Content-Type": "application/json"})
                resp = json.loads(c.getresponse().read())
                local.append(time.perf_counter() - t0)
                if not resp["response"]["allowed"]:
                    local_denied += 1
        except Exception as e:
            with lock:
                errors.append(f"worker {wid}: {type(e).__name__}: {e}")
        finally:
            c.close()
        with lock:
            latencies.extend(local)
            denied[0] += local_denied

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0

    lat_ms = sorted(x * 1000 for x in latencies)

    def pct(p):
        return lat_ms[min(len(lat_ms) - 1, int(p / 100 * len(lat_ms)))]

    hist_edges = [1, 2, 5, 10, 20, 50, 100, 200, 500, 1000]
    hist = {}
    for edge in hist_edges:
        hist[f"le_{edge}ms"] = sum(1 for x in lat_ms if x <= edge)
    out = {
        "metric": "webhook serving load",
        "errors": errors,
        "requests": len(lat_ms),
        "concurrency": conc,
        "elapsed_s": round(elapsed, 3),
        "requests_per_s": round(len(lat_ms) / elapsed, 1),
        "denied": denied[0],
        "p50_ms": round(pct(50), 2),
        "p90_ms": round(pct(90), 2),
        "p99_ms": round(pct(99), 2),
        "max_ms": round(lat_ms[-1], 2),
        "mean_ms": round(statistics.mean(lat_ms), 2),
        "histogram": hist,
        "batch_window_ms": 2.0,
        "server": "stdlib ThreadingHTTPServer (thread-per-connection; the "
                  "Batcher coalesces concurrent reviews so handler threads "
                  "block on the shared device pass, not on per-request "
                  "evaluation)",
    }
    print(json.dumps(out, indent=1))
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    with open(os.path.join(root, "WEBHOOK_LOAD.json"), "w") as f:
        f.write(json.dumps(out) + "\n")
    batcher.stop()
    srv.stop()


if __name__ == "__main__":
    main()
