"""Webhook serving-layer load test (VERDICT r1 #10).

Drives the real WebhookServer (TLS off) with concurrent AdmissionReview
POSTs over persistent connections, through the full stack: HTTP parse →
ValidationHandler → Batcher microbatch lane → device verdict grids →
deny/warn partition.  Reports throughput + a latency histogram and writes
WEBHOOK_LOAD.json at the repo root.

    JAX_PLATFORMS=cpu python tools/loadtest_webhook.py [n_requests] [conc]

The reference's concurrency model is goroutine-per-request capped by
--max-serving-threads (pkg/webhook/policy.go:116-120); here the cap is the
batch window — see the batch-size distribution in the output.
"""

from __future__ import annotations

import http.client
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_server():
    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import load_library
    from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
    from gatekeeper_tpu.webhook.server import WebhookServer

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    nt, nc = load_library(client)
    batcher = Batcher(client, window_s=0.002, max_batch=64).start()
    handler = ValidationHandler(client, batcher=batcher)
    # warm EVERY grid-lane pad bucket (9->16, 17->32, 33->64): shapes
    # otherwise compile lazily inside the first saturated lane
    # (seconds-long P99 spikes that say nothing about steady state)
    from gatekeeper_tpu.target.review import AugmentedUnstructured
    from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
    warm = [AugmentedUnstructured(
        object=json.loads(make_body(i))["request"]["object"],
        source=SOURCE_ORIGINAL) for i in range(batcher.max_batch)]
    n = max(1, batcher.small_batch + 1)
    while n <= batcher.max_batch:
        client.review_batch(warm[:n])
        n *= 2
    client.review_batch(warm)
    srv = WebhookServer(validation_handler=handler, port=0,
                        readiness_check=lambda: True).start()
    return srv, batcher, nt, nc


def make_body(i: int) -> bytes:
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    obj = make_cluster_objects(1, seed=i)[0]
    from gatekeeper_tpu.utils.unstructured import gvk_of

    g, v, k = gvk_of(obj)
    return json.dumps({
        "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
        "request": {
            "uid": f"u{i}", "operation": "CREATE",
            "kind": {"group": g, "version": v, "kind": k},
            "name": obj["metadata"].get("name", ""),
            "namespace": obj["metadata"].get("namespace", ""),
            "userInfo": {"username": "load"},
            "object": obj,
        },
    }).encode()


def run_load(port: int, bodies: list, n: int, conc: int) -> dict:
    """Drive ``n`` requests over ``conc`` persistent connections; return
    a stats dict (latency percentiles + throughput + histogram)."""
    latencies: list = []
    denied = [0]
    lock = threading.Lock()
    per_worker = n // conc
    errors: list = []

    def worker(wid: int):
        c = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
        local = []
        local_denied = 0
        try:
            for i in range(per_worker):
                body = bodies[(wid * per_worker + i) % len(bodies)]
                t0 = time.perf_counter()
                c.request("POST", "/v1/admit", body=body,
                          headers={"Content-Type": "application/json"})
                resp = json.loads(c.getresponse().read())
                local.append(time.perf_counter() - t0)
                if not resp["response"]["allowed"]:
                    local_denied += 1
        except Exception as e:
            with lock:
                errors.append(f"worker {wid}: {type(e).__name__}: {e}")
        finally:
            c.close()
        with lock:
            latencies.extend(local)
            denied[0] += local_denied

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(conc)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0

    lat_ms = sorted(x * 1000 for x in latencies)
    if not lat_ms:
        return {"errors": errors, "requests": 0, "concurrency": conc,
                "elapsed_s": round(elapsed, 3)}

    def pct(p):
        return lat_ms[min(len(lat_ms) - 1, int(p / 100 * len(lat_ms)))]

    hist_edges = [1, 2, 5, 10, 20, 50, 100, 200, 500, 1000]
    hist = {f"le_{e}ms": sum(1 for x in lat_ms if x <= e)
            for e in hist_edges}
    return {
        "errors": errors,
        "requests": len(lat_ms),
        "concurrency": conc,
        "elapsed_s": round(elapsed, 3),
        "requests_per_s": round(len(lat_ms) / elapsed, 1),
        "denied": denied[0],
        "p50_ms": round(pct(50), 2),
        "p90_ms": round(pct(90), 2),
        "p99_ms": round(pct(99), 2),
        "max_ms": round(lat_ms[-1], 2) if lat_ms else 0,
        "mean_ms": round(statistics.mean(lat_ms), 2) if lat_ms else 0,
        "histogram": hist,
    }


def warmup(port: int, bodies: list, k: int = 8) -> None:
    conn = http.client.HTTPConnection("127.0.0.1", port)
    for i in range(k):
        conn.request("POST", "/v1/admit", body=bodies[i % len(bodies)],
                     headers={"Content-Type": "application/json"})
        conn.getresponse().read()
    conn.close()


def serve_worker(port: int) -> None:
    """--worker mode: a full serving replica bound with SO_REUSEPORT;
    prints its served-request count on SIGTERM (the parent asserts the
    kernel spread load across replicas)."""
    import signal

    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.metrics.registry import MetricsRegistry
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import load_library
    from gatekeeper_tpu.webhook.policy import Batcher, ValidationHandler
    from gatekeeper_tpu.webhook.server import WebhookServer

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client)
    metrics = MetricsRegistry()
    batcher = Batcher(client, window_s=0.002, max_batch=64).start()
    handler = ValidationHandler(client, batcher=batcher, metrics=metrics)
    srv = WebhookServer(validation_handler=handler, port=port,
                        readiness_check=lambda: True,
                        reuse_port=True).start()
    print(f"worker {os.getpid()} on :{srv.port}", file=sys.stderr,
          flush=True)
    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    stop.wait()
    served = metrics.counter_total("validation_request_count")
    print(json.dumps({"pid": os.getpid(), "served": served}), flush=True)
    srv.stop()


def multi_worker_lane(bodies: list, n: int, conc: int,
                      n_workers: int = 2) -> dict:
    """SO_REUSEPORT lane: W independent serving processes share one port;
    the kernel balances connections.  Verifies every worker served
    traffic and reports aggregate throughput."""
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--worker", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    ) for _ in range(n_workers)]
    # wait for all workers to bind + warm
    deadline = time.time() + 300
    while time.time() < deadline:
        try:
            warmup(port, bodies, k=2)
            break
        except OSError:
            time.sleep(1.0)
    time.sleep(n_workers * 2)  # let every replica finish loading
    warmup(port, bodies, k=16)
    stats = run_load(port, bodies, n, conc)
    served = []
    for p in procs:
        p.terminate()
        out, _ = p.communicate(timeout=30)
        for line in out.splitlines():
            try:
                served.append(json.loads(line))
            except ValueError:
                pass
    stats["workers"] = served
    stats["all_workers_served"] = (
        len(served) == n_workers and all(w["served"] > 0 for w in served))
    return stats


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "--worker":
        serve_worker(int(sys.argv[2]))
        return
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    conc = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    srv, batcher, nt, nc = build_server()
    print(f"server on :{srv.port}; library {nt} templates / {nc} "
          f"constraints", file=sys.stderr)
    bodies = [make_body(i) for i in range(256)]
    warmup(srv.port, bodies)

    # lane 1: true per-request latency — one connection, no batch window
    # (the batcher still runs but a lone request never waits: the window
    # opens when the first request of a batch arrives)
    print("lane n1 (sequential, N=1)...", file=sys.stderr)
    lane_n1 = run_load(srv.port, bodies, min(n, 400), 1)
    # lane 2: moderate concurrency (a small cluster's admission load)
    print("lane conc8...", file=sys.stderr)
    lane_c8 = run_load(srv.port, bodies, n, 8)
    # lane 3: saturation (r2-comparable: 64 connections)
    print(f"lane conc{conc}...", file=sys.stderr)
    lane_sat = run_load(srv.port, bodies, n, conc)
    batcher.stop()
    srv.stop()
    # lane 4: SO_REUSEPORT multi-process serving
    print("lane multi-worker (SO_REUSEPORT x2)...", file=sys.stderr)
    lane_mw = multi_worker_lane(bodies, n, conc, n_workers=2)

    out = {
        "metric": "webhook serving load",
        "host_cpus": os.cpu_count(),
        "batch_window_ms": 2.0,
        "n1": lane_n1,
        "conc8": lane_c8,
        f"conc{conc}": lane_sat,
        "multiworker2": lane_mw,
        "server": "stdlib ThreadingHTTPServer (thread-per-connection; the "
                  "Batcher coalesces concurrent reviews so handler threads "
                  "block on the shared device pass, not on per-request "
                  "evaluation); SO_REUSEPORT worker processes for "
                  "multi-core hosts (--webhook-workers)",
        "note": "this bench host has ONE core: saturation latency is "
                "queueing delay (Little's law), and worker processes "
                "cannot add throughput here — the n1/conc8 lanes plus "
                "all_workers_served are the meaningful signals",
    }
    print(json.dumps(out, indent=1))
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    with open(os.path.join(root, "WEBHOOK_LOAD.json"), "w") as f:
        f.write(json.dumps(out) + "\n")


if __name__ == "__main__":
    main()
