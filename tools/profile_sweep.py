"""Phase-by-phase profile of one sweep chunk on the live device.

Usage: python tools/profile_sweep.py [n_objects] [chunk]
Times flatten / table build / H2D / dispatch+device / D2H separately so
tunnel-latency pathologies (77ms-per-fetch D2H) are attributable.
"""

import sys
import time

sys.path.insert(0, ".")


def main(n=32768, chunk=32768):
    from bench import build_client, log
    import jax
    import numpy as np

    log(f"devices: {jax.devices()}")
    client, tpu, nt, nc = build_client()
    from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                 make_mesh,
                                                 shard_batch_arrays,
                                                 shard_param_table)
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects
    from gatekeeper_tpu.ops.flatten import Flattener, Schema
    from gatekeeper_tpu.ir import masks as masks_mod
    from gatekeeper_tpu.ir.program import (build_param_table, needed_fields,
                                           pack_batch_cols, slim_cols,
                                           vocab_tables)
    from jax.sharding import NamedSharding, PartitionSpec as P

    objects = make_cluster_objects(n)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    cons = client.constraints()
    ev = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)

    # warm: the production path (interning + corpus col stats + compile,
    # fetch-free), then one timed warm sweep
    t0 = time.perf_counter()
    ev.warm_pass(cons, objects[:chunk], chunk)
    log(f"warm_pass (compile): {time.perf_counter()-t0:.1f}s")
    t0 = time.perf_counter()
    ev.sweep(cons, objects[:chunk])
    log(f"warm sweep: {time.perf_counter()-t0:.3f}s")

    # now phase by phase (mirrors sweep_submit)
    objs = objects[:chunk]
    by_kind = {}
    for con in cons:
        by_kind.setdefault(con.kind, []).append(con)
    lowered = [k for k in by_kind
               if k in tpu._programs and tpu.inventory_exact(k)]
    t0 = time.perf_counter()
    schema = Schema()
    for kind in lowered:
        schema.merge(tpu._programs[kind].program.schema)
    pad_n = ev._pad(len(objs))
    batch = Flattener(schema, tpu.vocab).flatten(objs, pad_n=pad_n)
    t_flatten = time.perf_counter() - t0

    from gatekeeper_tpu.parallel.sharded import (pack_flat_tables,
                                                 pack_transfer_cols)

    t0 = time.perf_counter()
    cols = pack_batch_cols(batch)
    cols = slim_cols(cols, ev._needs_union(lowered))
    any_gen = (bool(batch.has_generate_name[:len(objs)].any())
               if batch.has_generate_name is not None else False)
    kinds = tuple(sorted(lowered))
    tables = []
    mask_rows = []
    for kind in kinds:
        prog = tpu._programs[kind]
        kcons = by_kind[kind]
        tables.append(build_param_table(prog.program, kcons, tpu.vocab))
        mask_rows.append(masks_mod.constraint_masks(
            kcons, batch, tpu.vocab, objs, any_generate_name=any_gen))
    table_cols = {}
    for kind in kinds:
        for tk, tv in vocab_tables(tpu._programs[kind].program,
                                   tpu.vocab).items():
            table_cols[tk] = tv
        for tk, tv in tpu.inventory_cols(kind)[0].items():
            table_cols[tk] = tv
    cols_bufs, cols_layout = pack_transfer_cols(
        cols, pad_n, stats=ev._col_stats or None)
    tables_bufs, tables_layout = pack_flat_tables(tables)
    t_tables = time.perf_counter() - t0

    n_arrays = len(cols_bufs) + len(tables_bufs) + len(table_cols) + 1
    total_mb = sum(b.nbytes for b in cols_bufs.values()) / 1e6
    t0 = time.perf_counter()
    cols_bufs_dev = {
        dt: jax.device_put(b, NamedSharding(ev.mesh, P("data", None)))
        for dt, b in cols_bufs.items()}
    tables_bufs_dev = {
        dt: jax.device_put(b, NamedSharding(ev.mesh, P(None)))
        for dt, b in tables_bufs.items()}
    table_cols_dev = shard_batch_arrays(table_cols, ev.mesh,
                                        ev._table_dev_cache)
    mask = np.concatenate(mask_rows, axis=0)
    mask_dev = jax.device_put(mask, NamedSharding(ev.mesh, P(None, "data")))
    jax.block_until_ready(cols_bufs_dev)
    jax.block_until_ready(tables_bufs_dev)
    jax.block_until_ready(table_cols_dev)
    jax.block_until_ready(mask_dev)
    t_h2d = time.perf_counter() - t0

    fn = ev._sweep_fn(kinds, 20, False, cols_layout, tables_layout, pad_n)
    t0 = time.perf_counter()
    result = fn(tables_bufs_dev, cols_bufs_dev, table_cols_dev, mask_dev)
    jax.block_until_ready(result)
    t_device = time.perf_counter() - t0

    t0 = time.perf_counter()
    packed_np = np.asarray(result)
    t_d2h = time.perf_counter() - t0

    log(f"phases for chunk={chunk} ({len(kinds)} kinds, "
        f"{n_arrays} device transfers, {total_mb:.1f} MB H2D):")
    log(f"  flatten:       {t_flatten*1000:8.1f} ms")
    log(f"  tables+masks:  {t_tables*1000:8.1f} ms")
    log(f"  H2D:           {t_h2d*1000:8.1f} ms")
    log(f"  device+disp:   {t_device*1000:8.1f} ms")
    log(f"  D2H (packed):  {t_d2h*1000:8.1f} ms  ({packed_np.nbytes/1e3:.0f} KB)")
    tot = t_flatten + t_tables + t_h2d + t_device + t_d2h
    log(f"  TOTAL:         {tot*1000:8.1f} ms -> "
        f"{chunk/tot:,.0f} reviews/s extrapolated")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 32768,
         int(sys.argv[2]) if len(sys.argv) > 2 else 32768)
