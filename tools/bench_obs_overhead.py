"""Observability overhead bench: instrumented vs bare serving + sweep.

Measures the cost of the FULL observability stack — metrics registry
(bucketed histograms + exemplars), per-template cost attribution, the
admission flight recorder, and the keep-all span tracer — against the
bare path, on both enforcement surfaces:

- **webhook**: ``ValidationHandler.handle`` over an admission burst
  (the per-request seams: duration histogram, decision record, request
  spans, query_batch attribution);
- **sweep**: one library-corpus audit pass (the per-chunk seams:
  dispatch/flatten attribution, chunk spans, pipeline gauges);
- **degradation engine**: the bare webhook path with the targeted
  degradation maps ARMED but healthy (``--slo-degradation on``, a
  DegradationRegistry installed, an SLOEngine holding the default
  maps, nothing active) — the per-request cost of the
  ``degradation_active()`` checks the hot paths grew.

Passes interleave bare/instrumented (ABAB...) so clock drift and cache
warmth cancel, and the comparison uses medians.  Appends a history
entry to BENCH_TPU.json (``kind: obs_overhead``); the tier-1 smoke
(tests/test_obs_overhead.py) runs ``--smoke`` and asserts the serial
1-core overhead bound.

Usage: python tools/bench_obs_overhead.py [--objects N] [--passes K]
       [--smoke] [--no-append]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_setup(n_objects: int):
    from gatekeeper_tpu.apis.constraints import AUDIT_EP, WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import (load_library,
                                                make_cluster_objects)

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP, AUDIT_EP])
    load_library(client)
    objects = make_cluster_objects(n_objects, seed=41)
    return client, tpu, objects


def _bodies(objects):
    from gatekeeper_tpu.utils.unstructured import gvk_of

    out = []
    for i, obj in enumerate(objects):
        g, v, k = gvk_of(obj)
        out.append({
            "apiVersion": "admission.k8s.io/v1",
            "kind": "AdmissionReview",
            "request": {"uid": f"b{i}", "operation": "CREATE",
                        "kind": {"group": g, "version": v, "kind": k},
                        "name": (obj.get("metadata") or {}).get(
                            "name", ""),
                        "namespace": (obj.get("metadata") or {}).get(
                            "namespace", ""),
                        "userInfo": {"username": "bench"},
                        "object": obj},
        })
    return out


def _instrumented():
    """(contextmanager, registry): the full production observability
    stack, freshly installed."""
    import contextlib

    from gatekeeper_tpu.metrics.registry import MetricsRegistry
    from gatekeeper_tpu.observability import costattr, flightrec, tracing

    @contextlib.contextmanager
    def ctx():
        m = MetricsRegistry()
        attr = costattr.CostAttribution(metrics=m)
        rec = flightrec.FlightRecorder(metrics=m)
        tracer = tracing.Tracer(seed=0, ring_capacity=256)
        with tracing.activate(tracer), costattr.activate(attr), \
                flightrec.activate(rec):
            yield m
    return ctx


def run(n_objects: int = 200, passes: int = 5,
        append: bool = True) -> dict:
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.metrics.registry import MetricsRegistry
    from gatekeeper_tpu.parallel.sharded import (ShardedEvaluator,
                                                 make_mesh)
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    client, tpu, objects = build_setup(n_objects)
    bodies = _bodies(objects[: max(20, n_objects // 4)])
    mgr = AuditManager(
        client, lister=lambda: iter(objects),
        config=AuditConfig(chunk_size=64, exact_totals=False,
                           pipeline="off"),
        evaluator=ShardedEvaluator(tpu, make_mesh(),
                                   violations_limit=20))
    bare_handler = ValidationHandler(client)
    ctx = _instrumented()

    # warmup: vocab + jit compile outside every timed pass
    mgr.audit()
    for b in bodies[:4]:
        bare_handler.handle(b)

    from gatekeeper_tpu.observability import slo as slo_mod
    from gatekeeper_tpu.resilience import overload as ovl

    bare_web, inst_web, bare_sweep, inst_sweep = [], [], [], []
    deg_web = []
    # round 0 is a discarded warmup (lazy imports, first-touch caches on
    # BOTH variants) — medians are robust but the noise-spread guard the
    # smoke keys on must not see the one-time costs
    for rnd in range(passes + 1):
        t0 = time.perf_counter()
        for b in bodies:
            bare_handler.handle(b)
        bare_web.append(time.perf_counter() - t0)

        with ctx() as m:
            inst_handler = ValidationHandler(client, metrics=m)
            t0 = time.perf_counter()
            for b in bodies:
                inst_handler.handle(b)
            inst_web.append(time.perf_counter() - t0)

        # degradation-engine lane: registry installed + engine holding
        # the default maps, all objectives healthy — measures only the
        # armed checks (is_active reads) on the bare serving path
        reg = ovl.DegradationRegistry()
        eng = slo_mod.SLOEngine(MetricsRegistry(), degradations=reg)
        eng.tick()
        with ovl.activate_degradations(reg):
            t0 = time.perf_counter()
            for b in bodies:
                bare_handler.handle(b)
            deg_web.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        mgr.audit()
        bare_sweep.append(time.perf_counter() - t0)

        with ctx() as m:
            mgr.metrics = m
            t0 = time.perf_counter()
            mgr.audit()
            inst_sweep.append(time.perf_counter() - t0)
            mgr.metrics = None
        if rnd == 0:
            bare_web.clear()
            inst_web.clear()
            bare_sweep.clear()
            inst_sweep.clear()
            deg_web.clear()

    def med(xs):
        return statistics.median(xs)

    def spread(xs):
        # median absolute deviation relative to the median: how reliable
        # the median comparison is.  A single outlier pass (GC, page
        # cache, noisy neighbor) moves a max-min range wildly but barely
        # moves the MAD — and the comparison itself uses medians.
        m = med(xs)
        if not m:
            return 0.0
        return statistics.median(abs(x - m) for x in xs) / m

    entry = {
        "kind": "obs_overhead",
        "note": "instrumented (metrics+attribution+flightrec+tracer) "
                "vs bare, serial schedule",
        "date": time.strftime("%Y-%m-%d"),
        "platform": "cpu" if os.environ.get(
            "JAX_PLATFORMS", "") == "cpu" else "tpu",
        "host_cpus": os.cpu_count(),
        "objects": n_objects,
        "admissions": len(bodies),
        "passes": passes,
        "webhook_bare_s": round(med(bare_web), 4),
        "webhook_instrumented_s": round(med(inst_web), 4),
        "webhook_overhead_pct": round(
            100.0 * (med(inst_web) / med(bare_web) - 1.0), 2),
        "sweep_bare_s": round(med(bare_sweep), 4),
        "sweep_instrumented_s": round(med(inst_sweep), 4),
        "sweep_overhead_pct": round(
            100.0 * (med(inst_sweep) / med(bare_sweep) - 1.0), 2),
        # min-of-passes: scheduler noise strictly ADDS time, so the
        # fastest pass of each variant is the cleanest-machine estimate
        # — the tier-1 smoke asserts on these (median ratios jitter
        # several % on a busy 1-core host; minima are stable)
        "webhook_overhead_min_pct": round(
            100.0 * (min(inst_web) / min(bare_web) - 1.0), 2),
        "sweep_overhead_min_pct": round(
            100.0 * (min(inst_sweep) / min(bare_sweep) - 1.0), 2),
        # armed-but-healthy degradation maps vs bare: the marginal cost
        # of the degradation_active() reads on the serving path
        "webhook_degradation_armed_s": round(med(deg_web), 4),
        "degradation_overhead_pct": round(
            100.0 * (med(deg_web) / med(bare_web) - 1.0), 2),
        "degradation_overhead_min_pct": round(
            100.0 * (min(deg_web) / min(bare_web) - 1.0), 2),
        "noise_spread_pct": round(100.0 * max(
            spread(bare_web), spread(bare_sweep)), 2),
    }
    if append:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from bench import bench_history_append

        bench_history_append(entry)
    return entry


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--objects", type=int, default=200)
    p.add_argument("--passes", type=int, default=5)
    p.add_argument("--smoke", action="store_true",
                   help="small corpus, no history append (the tier-1 "
                        "smoke shape)")
    p.add_argument("--no-append", action="store_true")
    args = p.parse_args()
    if args.smoke:
        entry = run(n_objects=120, passes=3, append=False)
    else:
        entry = run(n_objects=args.objects, passes=args.passes,
                    append=not args.no_append)
    print(json.dumps(entry, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
