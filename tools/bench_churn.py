"""Template-churn storm bench: zero-stall generation swap vs inline compile.

The headline number for the generation-swap refactor (ROADMAP
"zero-stall template churn"): with admission bursts running nonstop, a
churn thread adds/removes ``n_churn`` templates mid-burst.  With
``--generation-swap off`` every add lowers + reshapes the union schema
inline and the first post-change batch retraces on the serving thread;
with ``on`` the churn stages + compiles on the background thread (warmed
at the real serving shapes) and swaps atomically — storm P99 must hold
within 2x the steady-state P99.

Also measures the on-disk compile cache's cold-start story: a fresh
driver against a warm ``CompileCache`` must perform ZERO lowering (every
template answered from disk with the vocab snapshot replayed).

Appends the previous latest record to the ``history`` list in
``CHURN_BENCH.json`` (the FLATTEN_BENCH convention).  Run:

    python tools/bench_churn.py [--smoke] [--out PATH]

``--smoke`` (small corpus, fewer bursts) runs in the slow lane via
tests/test_generation.py so the bench script itself cannot rot.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _build_client(generation_swap: bool, cache=None, skip_kinds=()):
    from gatekeeper_tpu.apis.constraints import WEBHOOK_EP
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import load_library

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel, generation_swap=generation_swap,
                    compile_cache=cache)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[WEBHOOK_EP])
    load_library(client, skip_kinds=skip_kinds)
    if tpu.gen_coord is not None:
        tpu.gen_coord.constraints_fn = client.constraints
    return client, tpu


def _churn_docs(n_churn: int):
    """The last n_churn library templates (template yaml + constraint
    yamls) — the storm removes and re-adds them."""
    import glob

    from gatekeeper_tpu.utils.synthetic import library_dir
    from gatekeeper_tpu.utils.unstructured import load_yaml_file

    out = []
    tpaths = sorted(
        glob.glob(os.path.join(library_dir(), "general", "*",
                               "template.yaml")) +
        glob.glob(os.path.join(library_dir(), "pod-security-policy", "*",
                               "template.yaml")))
    for tpath in tpaths[-n_churn:]:
        tdoc = load_yaml_file(tpath)[0]
        kind = (tdoc.get("spec", {}).get("crd", {}).get("spec", {})
                .get("names", {}).get("kind", ""))
        cons = []
        cpath = os.path.join(os.path.dirname(tpath), "samples",
                             "constraint.yaml")
        if os.path.exists(cpath):
            cons = load_yaml_file(cpath)
        out.append((kind, tdoc, cons))
    return out


def _percentiles(samples):
    if not samples:
        return {"p50_ms": None, "p99_ms": None, "n": 0}
    s = sorted(samples)
    return {
        "p50_ms": round(1e3 * s[len(s) // 2], 3),
        "p99_ms": round(1e3 * s[min(len(s) - 1,
                                    int(len(s) * 0.99))], 3),
        "mean_ms": round(1e3 * statistics.fmean(s), 3),
        "max_ms": round(1e3 * s[-1], 3),
        "n": len(s),
    }


def _run_mode(generation_swap: bool, objects, n_churn: int,
              steady_bursts: int, burst: int, churn_gap_s: float) -> dict:
    """One mode's storm measurement: burst loop on this thread, churn
    on another; latencies bucketed into steady vs storm windows."""
    from gatekeeper_tpu.match.match import SOURCE_ORIGINAL
    from gatekeeper_tpu.target.review import AugmentedUnstructured

    client, tpu = _build_client(generation_swap)
    docs = _churn_docs(n_churn)
    reviews = [AugmentedUnstructured(object=o, source=SOURCE_ORIGINAL)
               for o in objects[:burst]]
    coord = tpu.gen_coord
    if coord is not None:
        coord.start()

    # warm every serving shape, then measure the steady state
    for _ in range(3):
        client.review_batch(reviews)
    steady: list = []
    for _ in range(steady_bursts):
        t0 = time.perf_counter()
        client.review_batch(reviews)
        steady.append(time.perf_counter() - t0)

    storm: list = []
    errors = [0]
    done = threading.Event()

    def churn():
        # remove + re-add each doc: every edit reshapes the union schema
        try:
            for kind, tdoc, cons in docs:
                client.remove_template(kind)
                time.sleep(churn_gap_s)
                client.add_template(tdoc)
                for cdoc in cons:
                    client.add_constraint(cdoc)
                time.sleep(churn_gap_s)
        except Exception:
            errors[0] += 1
        finally:
            done.set()

    def storm_active():
        if not done.is_set():
            return True
        # swap mode: keep measuring while the background compile drains
        return coord is not None and coord.snapshot()["pending"]

    th = threading.Thread(target=churn, daemon=True)
    th.start()
    while storm_active():
        t0 = time.perf_counter()
        try:
            client.review_batch(reviews)
        except Exception:
            errors[0] += 1
        storm.append(time.perf_counter() - t0)
    th.join(30.0)
    if coord is not None:
        coord.wait_idle(30.0)
    # a couple of post-storm bursts: the first post-swap shapes
    post: list = []
    for _ in range(5):
        t0 = time.perf_counter()
        client.review_batch(reviews)
        post.append(time.perf_counter() - t0)
    if coord is not None:
        coord.stop()
    st = _percentiles(steady)
    sm = _percentiles(storm + post)
    ratio = (sm["p99_ms"] / st["p99_ms"]
             if st["p99_ms"] and sm["p99_ms"] else None)
    return {
        "mode": "on" if generation_swap else "off",
        "steady": st,
        "storm": sm,
        "p99_ratio": round(ratio, 2) if ratio else None,
        "burst_errors": errors[0],
        "swaps": coord.swap_count if coord is not None else 0,
    }


def _run_cache(smoke: bool) -> dict:
    """Cold start vs warm-cache start: lowering counts + wall."""
    from gatekeeper_tpu.drivers.generation import CompileCache

    import gatekeeper_tpu.drivers.tpu_driver as TD
    import gatekeeper_tpu.ir.lower_rego as LR

    with tempfile.TemporaryDirectory(prefix="gtpu-cc-") as d:
        cc1 = CompileCache(d)
        t0 = time.perf_counter()
        _build_client(False, cache=cc1)
        cold_s = time.perf_counter() - t0

        calls = [0]
        orig = LR.lower_template

        def counting(*a, **k):
            calls[0] += 1
            return orig(*a, **k)

        TD.lower_template = counting
        try:
            cc2 = CompileCache(d)
            t0 = time.perf_counter()
            _build_client(False, cache=cc2)
            warm_s = time.perf_counter() - t0
        finally:
            TD.lower_template = orig
        return {
            "cold_start_s": round(cold_s, 3),
            "warm_start_s": round(warm_s, 3),
            "cold": cc1.stats(),
            "warm": cc2.stats(),
            "warm_fresh_lowerings": calls[0],
        }


def run_bench(n_objects: int = 64, burst: int = 16, n_churn: int = 10,
              steady_bursts: int = 60, churn_gap_s: float = 0.01,
              out_path: str = None, seed: int = 31,
              write: bool = True) -> dict:
    from gatekeeper_tpu.utils.synthetic import make_cluster_objects

    objects = make_cluster_objects(n_objects, seed=seed)
    record = {
        "kind": "churn_bench",
        "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host_cpus": os.cpu_count() or 1,
        "n_objects": n_objects,
        "burst": burst,
        "templates_churned": n_churn,
        "steady_bursts": steady_bursts,
        "modes": {},
    }
    for swap in (False, True):
        m = _run_mode(swap, objects, n_churn, steady_bursts, burst,
                      churn_gap_s)
        record["modes"][m["mode"]] = m
    record["cache"] = _run_cache(smoke=steady_bursts < 30)
    on = record["modes"]["on"]
    record["headline"] = {
        "storm_p99_within_2x_steady": (
            on["p99_ratio"] is not None and on["p99_ratio"] <= 2.0),
        "p99_ratio_on": on["p99_ratio"],
        "p99_ratio_off": record["modes"]["off"]["p99_ratio"],
        "warm_start_zero_lowering":
            record["cache"]["warm_fresh_lowerings"] == 0,
    }
    if write:
        out = out_path or os.path.join(os.path.dirname(__file__), "..",
                                       "CHURN_BENCH.json")
        history = []
        if os.path.exists(out):
            try:
                with open(out) as fh:
                    prev = json.load(fh)
                history = prev.pop("history", [])
                history.append(prev)  # previous latest becomes history
            except Exception:
                history = []
        record_out = dict(record)
        record_out["history"] = history
        with open(out, "w") as fh:
            json.dump(record_out, fh, indent=1)
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    smoke = "--smoke" in argv
    argv = [a for a in argv if a != "--smoke"]
    out = None
    if "--out" in argv:
        i = argv.index("--out")
        out = argv[i + 1]
        del argv[i: i + 2]
    if smoke:
        rec = run_bench(n_objects=24, burst=8, n_churn=3,
                        steady_bursts=12, out_path=out,
                        write=out is not None)
    else:
        rec = run_bench(out_path=out)
    print(json.dumps({"headline": rec["headline"],
                      "on": rec["modes"]["on"],
                      "off": rec["modes"]["off"],
                      "cache": rec["cache"]}, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
