"""Churn-replay snapshot bench: O(churn) vs O(cluster) sweep cost.

Builds the library client + a synthetic cluster in a FakeCluster, then
measures the three audit costs the ROADMAP's incremental-audit item
cares about:

- ``relist_sweep_s``   — a relist-mode full sweep (list + flatten +
  device eval every pass, the pre-snapshot shape);
- ``snapshot_full_s``  — a snapshot-mode full pass (resident columns
  slice straight into device chunks: zero list/flatten);
- ``tick_s``           — a steady-state incremental tick after a seeded
  churn burst dirties ``churn_fraction`` of the rows (the O(churn)
  number);
- ``resync_s``         — the full-resync differential (fresh relist +
  re-flatten + per-row signature compare + verdict differential), the
  periodic consistency proof's price tag.

``--spill`` adds the cold-start lane (snapshot/persist.py): the
resident state spills to disk, then a FRESH snapshot boots twice —
once the relist way (rebuild: list + flatten + evaluate everything)
and once the spill way (load columns + verdicts from disk, first tick
evaluates nothing) — and the record carries
``relist_boot_s`` / ``spill_boot_s`` / ``spill_boot_vs_relist``.

Appends the previous latest record to the ``history`` list in
``SNAPSHOT_BENCH.json`` (the FLATTEN_BENCH convention).  Run:

    python tools/bench_snapshot.py [n_objects] [churn_fraction] [--spill]

A ``--smoke`` invocation (tiny corpus, one tick) runs in tier-1 via
tests/test_snapshot.py so the bench script itself cannot rot; the
spill lane's smoke runs in tests/test_snapshot_persist.py and pins
spill-load boot < 0.5x relist boot.
"""

from __future__ import annotations

import copy
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def run_bench(n_objects: int = 20_000, churn_fraction: float = 0.01,
              ticks: int = 3, chunk_size: int = 2048,
              out_path: str = None, seed: int = 11,
              write: bool = True, spill: bool = False) -> dict:
    from gatekeeper_tpu.apis.constraints import AUDIT_EP
    from gatekeeper_tpu.audit.manager import AuditConfig, AuditManager
    from gatekeeper_tpu.client.client import Client
    from gatekeeper_tpu.drivers.cel_driver import CELDriver
    from gatekeeper_tpu.drivers.tpu_driver import TpuDriver
    from gatekeeper_tpu.parallel.sharded import ShardedEvaluator, make_mesh
    from gatekeeper_tpu.snapshot import (ClusterSnapshot, SnapshotConfig,
                                         WatchIngester, gvks_of)
    from gatekeeper_tpu.sync.source import FakeCluster
    from gatekeeper_tpu.target.target import K8sValidationTarget
    from gatekeeper_tpu.utils.synthetic import (iter_cluster_objects,
                                                load_library,
                                                make_cluster_objects)

    cel = CELDriver()
    tpu = TpuDriver(cel_driver=cel)
    client = Client(target=K8sValidationTarget(), drivers=[tpu, cel],
                    enforcement_points=[AUDIT_EP])
    nt, nc = load_library(client)
    objects = make_cluster_objects(n_objects, seed=seed)
    for o in objects:
        if o.get("kind") == "Ingress":
            client.add_data(o)
    cluster = FakeCluster()
    for o in objects:
        cluster.apply(o)

    def lister():
        return iter(cluster.list())

    evaluator = ShardedEvaluator(tpu, make_mesh(), violations_limit=20)

    # --- relist baseline (serial schedule; one warm + one timed) -------
    relist_mgr = AuditManager(
        client, lister=lister,
        config=AuditConfig(chunk_size=chunk_size, exact_totals=False,
                           pipeline="off"),
        evaluator=evaluator)
    relist_mgr.audit()  # compile warmup
    t0 = time.perf_counter()
    relist_run = relist_mgr.audit()
    relist_s = time.perf_counter() - t0

    # --- snapshot mode --------------------------------------------------
    snapshot = ClusterSnapshot(evaluator, SnapshotConfig())
    snap_mgr = AuditManager(
        client, lister=lister,
        config=AuditConfig(chunk_size=chunk_size, exact_totals=False,
                           pipeline="off", audit_source="snapshot"),
        evaluator=evaluator, snapshot=snapshot)
    ingester = WatchIngester(snapshot, cluster,
                             gvks_of(cluster.list())).start()
    snap_mgr.audit()  # build + first full pass (also compile warmup)
    t0 = time.perf_counter()
    snap_run = snap_mgr.audit()
    snap_full_s = time.perf_counter() - t0
    assert snap_run.total_violations == relist_run.total_violations, \
        "snapshot/relist verdict mismatch (bench aborted)"

    # --- steady-state churn ticks ---------------------------------------
    churn_n = max(1, int(n_objects * churn_fraction))
    rng_names = [o["metadata"]["name"] for o in objects]
    tick_times: list = []
    tick_rows: list = []
    fresh = iter(iter_cluster_objects(ticks * churn_n, seed=seed + 99))
    for t in range(ticks):
        # a churn burst: ~1/3 modifies, ~1/3 adds, ~1/3 deletes-and-readds
        for j in range(churn_n):
            which = j % 3
            if which == 0:
                o = copy.deepcopy(objects[(t * churn_n + j)
                                          % len(objects)])
                meta = o.setdefault("metadata", {})
                labels = meta.setdefault("labels", {})
                labels["churn"] = f"t{t}-{j}"
                cluster.apply(o)
            elif which == 1:
                o = next(fresh)
                o["metadata"]["name"] = \
                    f"{o['metadata']['name']}-churn-{t}-{j}"
                cluster.apply(o)
            else:
                name = rng_names[(t * churn_n + j) % len(rng_names)]
                victim = next((ob for ob in cluster.list()
                               if ob["metadata"].get("name") == name),
                              None)
                if victim is not None:
                    cluster.delete(victim)
        ingester.pump()
        dirty = snapshot.dirty_count()
        t0 = time.perf_counter()
        snap_mgr.audit_tick()
        tick_times.append(time.perf_counter() - t0)
        tick_rows.append(dirty)

    # --- resync differential --------------------------------------------
    t0 = time.perf_counter()
    snap_mgr.audit_resync()
    resync_s = time.perf_counter() - t0
    ingester.stop()

    # --- cold-start lane: relist boot vs spill-load boot ----------------
    spill_stats = None
    if spill:
        import tempfile

        from gatekeeper_tpu.snapshot import (SnapshotSpill,
                                             templates_digest)

        tdig = templates_digest(client)
        spill_dir = tempfile.mkdtemp(prefix="gtpu-spill-")
        sp = SnapshotSpill(spill_dir)
        wrote = sp.save(snapshot, templates=tdig)
        cons = [c for c in client.constraints() if c.actions_for(AUDIT_EP)]

        def boot(warm: bool) -> tuple:
            """(wall seconds, totals) of the first completed audit pass
            of a FRESH snapshot: the relist way (rebuild + evaluate
            everything) or the spill way (load from disk, tick
            evaluates nothing).  The evaluator is shared (already
            compiled/traced) so the lane isolates the DATA-plane boot
            cost — the compile side is PR 12's story."""
            snap_b = ClusterSnapshot(evaluator, SnapshotConfig())
            mgr_b = AuditManager(
                client, lister=lister,
                config=AuditConfig(chunk_size=chunk_size,
                                   exact_totals=False, pipeline="off",
                                   audit_source="snapshot"),
                evaluator=evaluator, snapshot=snap_b)
            t0 = time.perf_counter()
            if warm:
                loaded = SnapshotSpill(spill_dir).load(
                    snap_b, cons, templates=tdig)
                assert loaded is not None, "spill-load boot missed"
                run_b = mgr_b.audit_tick()
            else:
                run_b = mgr_b.audit()
            return time.perf_counter() - t0, run_b.total_violations

        relist_boot_s, totals_relist = boot(warm=False)
        spill_boot_s, totals_spill = boot(warm=True)
        assert totals_spill == totals_relist, \
            "spill-load boot verdicts diverged from relist boot"
        spill_stats = {
            "spill_write_s": round(wrote.get("seconds", 0.0), 4),
            "spill_bytes": wrote.get("bytes", 0),
            "relist_boot_s": round(relist_boot_s, 4),
            "spill_boot_s": round(spill_boot_s, 4),
            "spill_boot_vs_relist": round(
                spill_boot_s / max(relist_boot_s, 1e-9), 4),
        }

    tick_med = statistics.median(tick_times)
    record = {
        "n_objects": n_objects,
        "churn_fraction": churn_fraction,
        "churn_per_tick": churn_n,
        "ticks": ticks,
        "chunk_size": chunk_size,
        "templates": nt,
        "constraints": nc,
        "host_cpus": os.cpu_count() or 1,
        "date": time.strftime("%Y-%m-%d"),
        "relist_sweep_s": round(relist_s, 4),
        "snapshot_full_s": round(snap_full_s, 4),
        "tick_s_median": round(tick_med, 4),
        "tick_s_all": [round(x, 4) for x in tick_times],
        "tick_dirty_rows": tick_rows,
        "resync_s": round(resync_s, 4),
        "resync_ok": snap_mgr.last_resync_diff is None,
        "snapshot_rows": snapshot.stats()["rows"],
        "tick_vs_relist_speedup": round(relist_s / max(tick_med, 1e-9),
                                        1),
        "full_vs_relist_speedup": round(relist_s / max(snap_full_s,
                                                       1e-9), 2),
    }
    if spill_stats is not None:
        record.update(spill_stats)
    if write:
        path = out_path or os.path.join(os.path.dirname(__file__), "..",
                                        "SNAPSHOT_BENCH.json")
        history = []
        try:
            with open(path) as fh:
                prev = json.load(fh)
            history = prev.pop("history", [])
            history.append(prev)  # the previous latest becomes history
        except (OSError, ValueError):
            pass
        record_out = dict(record)
        record_out["history"] = history
        with open(path, "w") as fh:
            json.dump(record_out, fh, indent=1)
        print(json.dumps({
            "metric": "incremental tick vs relist sweep",
            "value": record["tick_vs_relist_speedup"],
            "unit": "x faster",
            "tick_s": record["tick_s_median"],
            "relist_sweep_s": record["relist_sweep_s"],
        }))
    return record


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    smoke = "--smoke" in argv
    spill = "--spill" in argv
    argv = [a for a in argv if a not in ("--smoke", "--spill")]
    if smoke:
        rec = run_bench(n_objects=120, churn_fraction=0.05, ticks=1,
                        chunk_size=64, write=False, spill=spill)
        assert rec["resync_ok"], "smoke resync diverged"
        out = {"smoke": True, "tick_s": rec["tick_s_median"],
               "rows": rec["snapshot_rows"]}
        if spill:
            out["spill_boot_vs_relist"] = rec["spill_boot_vs_relist"]
        print(json.dumps(out))
        return 0
    n = int(argv[0]) if argv else 20_000
    churn = float(argv[1]) if len(argv) > 1 else 0.01
    run_bench(n_objects=n, churn_fraction=churn, spill=spill)
    return 0


if __name__ == "__main__":
    sys.exit(main())
