"""Native-module lint: warning-clean and sanitizer-clean C kernels.

Two gates over ``native/flattenmod.c`` and ``native/flattenjsonmod.c``:

- **strict compile** — both modules must build with
  ``-Wall -Wextra -Werror`` (a warning in kernel code is a bug
  waiting for a compiler upgrade to find it);
- **sanitizer corpus run** (slow) — rebuild the modules with
  ``-fsanitize=address,undefined`` through the normal
  ``ops/native.py`` build (the flag set hashes into the output dir,
  so the sanitized build can never be satisfied by a stale plain
  binary) and run the flatten unit corpus under it in a subprocess
  with libasan preloaded.  Memory errors or UB in the threaded
  kernel abort the run.

Run standalone (``python tools/lint_native.py [--asan]``) or via
tier-1 (``tests/test_native_lint.py``; the sanitizer gate is
slow-marked).
"""

from __future__ import annotations

import os
import subprocess
import sys
import sysconfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SOURCES = ("flattenmod.c", "flattenjsonmod.c")
STRICT_FLAGS = ["-Wall", "-Wextra", "-Werror"]


def _cc() -> list:
    return (sysconfig.get_config_var("CC") or "cc").split()


def _includes() -> list:
    import numpy as np

    return [f"-I{sysconfig.get_path('include')}", f"-I{np.get_include()}"]


def compile_strict(src_file: str) -> tuple:
    """(ok, compiler output) for one source under -Wall -Wextra -Werror."""
    src = os.path.join(REPO, "native", src_file)
    cmd = (_cc() + ["-c", "-O2", "-fPIC", "-pthread"] + STRICT_FLAGS
           + [src, "-o", os.devnull] + _includes())
    proc = subprocess.run(cmd, capture_output=True, text=True)
    return proc.returncode == 0, (proc.stderr or proc.stdout)


def find_libasan() -> str:
    """Path to libasan for LD_PRELOAD, or "" when the toolchain has
    none (the sanitizer gate skips)."""
    try:
        proc = subprocess.run(_cc() + ["-print-file-name=libasan.so"],
                              capture_output=True, text=True)
    except OSError:
        return ""
    path = (proc.stdout or "").strip()
    return path if path and os.path.sep in path and os.path.exists(path) \
        else ""


def asan_corpus_run(timeout_s: float = 600.0) -> tuple:
    """(ok, output): run the flatten unit corpus against an
    ASan+UBSan build of both native modules in a subprocess."""
    libasan = find_libasan()
    if not libasan:
        return True, "skipped: libasan not found in the toolchain"
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        # the flag-digest build dir (ops/native._build) keys on this:
        # the sanitized build lands beside, never instead of, the
        # production binary
        "GTPU_NATIVE_CFLAGS":
            "-fsanitize=address,undefined -fno-sanitize-recover=all "
            "-fno-omit-frame-pointer",
        "LD_PRELOAD": libasan,
        # leak checking is off: the interpreter itself "leaks" at exit
        # and the context pool/vocab mirror intentionally persist
        "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
    })
    cmd = [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
           os.path.join(REPO, "tests", "test_native_flatten_json.py"),
           os.path.join(REPO, "tests", "test_native_flatten.py")]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout_s, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        return False, f"sanitizer corpus run timed out after {timeout_s}s"
    out = (proc.stdout or "") + (proc.stderr or "")
    return proc.returncode == 0, out[-4000:]


def main() -> int:
    rc = 0
    for src in SOURCES:
        ok, out = compile_strict(src)
        if ok:
            print(f"strict compile clean: native/{src}")
        else:
            print(f"lint: native/{src} fails -Wall -Wextra -Werror:\n{out}",
                  file=sys.stderr)
            rc = 1
    if "--asan" in sys.argv[1:]:
        ok, out = asan_corpus_run()
        if ok:
            print(f"sanitizer corpus run: {out if 'skipped' in out else 'clean'}")
        else:
            print(f"lint: sanitizer corpus run failed:\n{out}",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
