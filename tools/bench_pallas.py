"""Measure the Pallas verdict-epilogue kernel against the XLA top_k twin
on the live device, at the sweep's real shapes.

    python tools/bench_pallas.py [C] [N] [k]

Both paths run under one jit (as the fused sweep calls them), timed over
repeated dispatches with block_until_ready.  Writes PALLAS_BENCH.json.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def main(c=46, n=32768, k=20, iters=50):
    from gatekeeper_tpu.ops.pallas_topk import topk_violations_pallas
    from gatekeeper_tpu.parallel.sharded import topk_violations

    print(f"devices: {jax.devices()}", file=sys.stderr)
    rng = np.random.default_rng(0)
    grid = jnp.asarray(rng.random((c, n)) < 0.05)

    def packed(fn):
        @jax.jit
        def run(g):
            idx, valid = fn(g, k)
            counts = jnp.sum(g, axis=1, dtype=jnp.int32)
            return jnp.concatenate(
                [idx, valid.astype(jnp.int32), counts[:, None]], axis=1)
        return run

    out = {"C": c, "N": n, "k": k, "iters": iters,
           "platform": jax.devices()[0].platform}
    results = {}
    for name, fn in (("xla_topk", topk_violations),
                     ("pallas", topk_violations_pallas)):
        run = packed(fn)
        r = run(grid)
        jax.block_until_ready(r)  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            r = run(grid)
        jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / iters
        results[name] = dt * 1e6
        print(f"{name}: {dt*1e6:.0f} us/call", file=sys.stderr)
    out["us_per_call"] = results
    out["speedup_pallas_vs_xla"] = round(
        results["xla_topk"] / results["pallas"], 3)
    with open(os.path.join(os.path.dirname(__file__), "..",
                           "PALLAS_BENCH.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:5]))
