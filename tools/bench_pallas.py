"""Measure the Pallas verdict-epilogue kernels against their XLA twins
on the live device, at the sweep's real shapes.

    python tools/bench_pallas.py [C] [N] [k]

Two lanes, both under one jit (as the fused sweep calls them), timed
over repeated dispatches with block_until_ready:

- **topk** — ``topk_violations_pallas`` vs the XLA ``top_k`` fold over
  an already-masked grid (the classic epilogue);
- **fused_fold** — ``fused_fold_pallas(grid_raw, mask, k)`` vs the XLA
  reference fold (mask apply -> violation totals -> top-k -> occupancy
  as separate XLA ops): the resident-tick epilogue, where the raw
  verdict block and the match mask meet in one VMEM pass.

Writes PALLAS_BENCH.json: every run appends to ``history`` with its
platform + date; the top-level headline only moves for real-TPU runs
(interpret-mode CPU numbers measure the interpreter, not the kernel).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

_OUT = os.path.join(os.path.dirname(__file__), "..", "PALLAS_BENCH.json")


def _timed(run, arg, iters):
    r = run(*arg)
    jax.block_until_ready(r)  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        r = run(*arg)
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / iters * 1e6


def _topk_lane(c, n, k, iters):
    from gatekeeper_tpu.ops.pallas_topk import topk_violations_pallas
    from gatekeeper_tpu.parallel.sharded import topk_violations

    rng = np.random.default_rng(0)
    grid = jnp.asarray(rng.random((c, n)) < 0.05)

    def packed(fn):
        @jax.jit
        def run(g):
            idx, valid = fn(g, k)
            counts = jnp.sum(g, axis=1, dtype=jnp.int32)
            return jnp.concatenate(
                [idx, valid.astype(jnp.int32), counts[:, None]], axis=1)
        return run

    results = {}
    for name, fn in (("xla_topk", topk_violations),
                     ("pallas", topk_violations_pallas)):
        results[name] = _timed(packed(fn), (grid,), iters)
        print(f"topk/{name}: {results[name]:.0f} us/call",
              file=sys.stderr)
    return results


def _fused_fold_lane(c, n, k, iters):
    from gatekeeper_tpu.ops.pallas_topk import fused_fold_pallas
    from gatekeeper_tpu.parallel.sharded import topk_violations

    rng = np.random.default_rng(1)
    grid = jnp.asarray(rng.random((c, n)) < 0.05)
    mask = jnp.asarray(rng.random((c, n)) < 0.7)

    @jax.jit
    def xla_ref(g, m):
        masked = g & m
        idx, valid = topk_violations(masked, k)
        return jnp.concatenate(
            [idx, valid.astype(jnp.int32),
             jnp.sum(masked, axis=1, dtype=jnp.int32)[:, None],
             jnp.sum(m, axis=1, dtype=jnp.int32)[:, None]], axis=1)

    @jax.jit
    def fused(g, m):
        idx, valid, cnt, occ = fused_fold_pallas(g, m, k)
        return jnp.concatenate(
            [idx, valid.astype(jnp.int32), cnt[:, None], occ[:, None]],
            axis=1)

    results = {}
    for name, fn in (("xla_fold", xla_ref), ("pallas_fused", fused)):
        results[name] = _timed(fn, (grid, mask), iters)
        print(f"fused_fold/{name}: {results[name]:.0f} us/call",
              file=sys.stderr)
    return results


def _history_append(entry: dict) -> None:
    """Append to PALLAS_BENCH.json's history; the headline only moves
    for real-TPU runs (same convention as BENCH_TPU/SWEEP1M)."""
    try:
        with open(_OUT) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    history = doc.pop("history", [])
    headline = doc if doc.get("us_per_call") or doc.get("topk") else {}
    entry = dict(entry)
    entry["date"] = time.strftime("%Y-%m-%d")
    history.append(entry)
    if entry.get("platform") == "tpu":
        headline = {k: v for k, v in entry.items() if k != "date"}
    out_doc = dict(headline)
    out_doc["history"] = history
    with open(_OUT, "w") as f:
        json.dump(out_doc, f, indent=1)
        f.write("\n")


def main(c=46, n=32768, k=20, iters=50):
    print(f"devices: {jax.devices()}", file=sys.stderr)
    out = {"C": c, "N": n, "k": k, "iters": iters,
           "platform": jax.devices()[0].platform}
    out["topk"] = _topk_lane(c, n, k, iters)
    out["speedup_pallas_vs_xla"] = round(
        out["topk"]["xla_topk"] / out["topk"]["pallas"], 3)
    out["fused_fold"] = _fused_fold_lane(c, n, k, iters)
    out["speedup_fused_vs_xla_fold"] = round(
        out["fused_fold"]["xla_fold"] / out["fused_fold"]["pallas_fused"],
        3)
    _history_append(out)
    print(json.dumps(out))


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:5]))
