#!/usr/bin/env python
"""Adversarial soak CLI: the corpus + chaos harness as one command.

    python tools/soak.py --seed 0 --minutes 0 --families all --chaos on

A zero-``--minutes`` run is a single full pass over every selected
family (the tier-1 smoke shape); ``--minutes N`` loops rounds until the
clock runs out (the multi-core soak).  Every failure prints the exact
repro line; the run is recorded to ``tools/SOAK_BENCH.json`` with the
bench-standard history list (previous record appended under
``history``), corpus stats, and ``host_cpus`` so numbers from 1-core
and many-core hosts never get compared blind.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
# assignment, not setdefault: the ambient env may say "axon" and the
# package import hook honors JAX_PLATFORMS — a dead tunnel would hang
# the whole soak (the fuzz_differential.py precedent)
os.environ["JAX_PLATFORMS"] = "cpu"

DEFAULT_OUT = os.path.join(os.path.dirname(__file__), "SOAK_BENCH.json")


def main() -> int:
    from gatekeeper_tpu.fuzz import corpus
    from gatekeeper_tpu.fuzz.soak import _repro_line, run_soak

    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--size", type=int, default=1,
                   help="corpus size dial (1 = smoke, 16+ = ~1MB objects)")
    p.add_argument("--minutes", type=float, default=0.0,
                   help="0 = one full pass; >0 loops rounds on the clock")
    p.add_argument("--rounds", type=int, default=1,
                   help="passes when --minutes is 0")
    p.add_argument("--families", default="all",
                   help="comma list out of: " + ",".join(corpus.FAMILIES))
    p.add_argument("--chaos", default="on",
                   help="'on' (plan seeded by --seed), 'off', or an "
                        "integer chaos seed")
    p.add_argument("--concurrent", action="store_true",
                   help="drive admit/mutate from threads while the "
                        "audit runs (multi-core hosts)")
    p.add_argument("--inject-bug", default=None,
                   choices=["mutate_program", "extdata_column"],
                   help="seeded-bug sensitivity check: the run MUST "
                        "report a divergence")
    p.add_argument("--residency", default="off",
                   choices=["off", "auto", "on"],
                   help="arm the device-resident snapshot lane on the "
                        "snapshot-side audit (single-device mesh)")
    p.add_argument("--out", default=DEFAULT_OUT,
                   help="bench record path ('' disables recording)")
    args = p.parse_args()

    families = (None if args.families in ("all", "") else
                [f.strip() for f in args.families.split(",") if f.strip()])
    chaos = args.chaos != "off"
    chaos_seed = (int(args.chaos) if chaos and args.chaos != "on"
                  else None)

    report = run_soak(
        seed=args.seed, size=args.size, families=families,
        duration_s=args.minutes * 60.0, rounds=args.rounds,
        chaos=chaos, chaos_seed=chaos_seed, inject_bug=args.inject_bug,
        concurrent=args.concurrent, quiet=True,
        residency=args.residency)

    if args.inject_bug:
        # sensitivity inversion: the seeded bug MUST have been caught
        caught = bool(report["divergences"])
        report["ok"] = caught
        print("seeded bug "
              + ("CAUGHT" if caught else "MISSED — harness is blind"))

    print(json.dumps(report, indent=2, default=str))
    if args.out:
        record = {
            "kind": "soak",
            "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                       time.gmtime()),
            "host_cpus": os.cpu_count(),
            "seed": report["seed"],
            "size": report["size"],
            "families": report["families"],
            "rounds": report["rounds"],
            "chaos": report["chaos"],
            "inject_bug": report["inject_bug"],
            "requests": report["requests"],
            "lost_verdicts": report["lost_verdicts"],
            "drain_ok": report["drain_ok"],
            "divergences_found": len(report["divergences"]),
            "crashes": len(report["crashes"]),
            "corpus": report["corpus"],
            "wall_s": report["wall_s"],
            "ok": report["ok"],
        }
        history = []
        if os.path.exists(args.out):
            with open(args.out) as f:
                prev = json.load(f)
            history = prev.pop("history", [])
            history.append(prev)
        record["history"] = history
        with open(args.out, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        print(f"recorded -> {args.out}")
    if not report["ok"]:
        print(_repro_line(report))
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
